"""Startup kernel auto-selection and per-shape-class tile autotuning.

BENCH_r05 measured the Pallas paged-attention decode kernel *losing* to the
XLA gathered-einsum path on real hardware (kernel_speedup 0.91) — which
path wins depends on generation/shape, so "auto" times both on the live
backend at engine startup and picks the winner.  The ragged kernel serves
three distinct shape classes (decode rows, spec ``[B, k+1]`` verify
windows, prefill chunks) whose arithmetic intensity differs wildly, so each
class is probed separately and gets its own ``attention_impl_{class}``
choice.  The probe is one small attention call per (impl, class) — tens of
ms total, not a model forward.

On top of the impl choice, this module sweeps the ragged kernel's
``(q_tile, kv_tile)`` tile space per shape class (ROADMAP item 2 — the
*Ragged Paged Attention* paper's win is exactly this per-shape grid
tuning).  Every candidate must pass a parity gate before it is eligible to
win: on CPU the sweep harness runs each candidate in Pallas interpret mode
and compares against an order-exact reference bit-for-bit (see
``reference_ragged``); at TPU runtime candidates are gated numerically
against the gathered-einsum path.  Winners are persisted in a JSON cache
(``DYNTPU_AUTOTUNE_CACHE``) keyed by a hash of (ModelConfig, EngineConfig
shape fields, device_kind, jax version) so startup pays the sweep once per
configuration and bench/serving share winners; a config drift changes the
key and falls back to defaults instead of replaying stale winners.

On non-TPU backends the choice is einsum without probing: Pallas only runs
in interpret mode there, which is orders of magnitude slower and would both
waste startup time and always lose anyway.

Run ``python -m dynamo_tpu.engine.autotune`` (CPU, with
``XLA_FLAGS=--xla_disable_hlo_passes=fusion``) to print the JSON parity
report the ``tune`` test suite asserts on.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import json
import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.logging import get_logger
from .config import EngineConfig, ModelConfig

log = get_logger("autotune")

# persisted sweep-winner cache path ("" / unset = no persistence)
CACHE_ENV = "DYNTPU_AUTOTUNE_CACHE"
# set to 0 to skip the startup tile sweep (impl probe still runs)
SWEEP_ENV = "DYNTPU_AUTOTUNE_SWEEP"
CACHE_VERSION = 1

# minimum second-to-minor tile dim per dtype (pallas_guide.md): kv_tile is
# the second-to-last axis of the (1, KV, kv_tile, hd) K/V block.  Quantized
# paged caches store 1-byte elements, whose native tile is (32, 128).
_SUBLANE = {"float32": 8, "bfloat16": 16, "int8": 32, "fp8": 32}


def _time_attention(fn, args, iters: int = 20) -> float:
    fn(*args).block_until_ready()  # warm (compile)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    out.block_until_ready()
    return (time.perf_counter() - t0) / iters * 1e3


# ---------------------------------------------------------------------------
# impl microprobe (pallas vs einsum per shape class)
# ---------------------------------------------------------------------------


def _probe_class(
    model_config: ModelConfig, engine_config: EngineConfig,
    B: int, T: int,
) -> dict:
    """Time ragged-Pallas vs gathered-einsum on a ``[B, T]`` chunk shape.

    Rows attend a full ``W * block_size`` context (the chunk is its last
    ``T`` tokens) — the worst case for the einsum path's gathered scores
    and the steady state for the kernel's block streaming.
    """
    import jax
    import jax.numpy as jnp

    from ..ops.paged_attention import (
        paged_attention_decode, paged_attention_ragged,
    )
    from . import model as model_lib

    bs = engine_config.block_size
    W = max(2, min(8, engine_config.max_blocks_per_seq))
    KV = model_config.num_kv_heads
    H = model_config.num_heads
    hd = model_config.head_dim_
    NB = 1 + B * W
    rng = np.random.default_rng(0)
    dt = jnp.bfloat16 if model_config.dtype == "bfloat16" else jnp.float32
    q = jnp.asarray(rng.standard_normal((B, T, H, hd)), dt)
    k = jnp.asarray(rng.standard_normal((NB, KV, bs, hd)), dt)
    v = jnp.asarray(rng.standard_normal((NB, KV, bs, hd)), dt)
    tables = jnp.asarray(1 + np.arange(B * W).reshape(B, W), jnp.int32)
    lens = jnp.full((B,), W * bs, jnp.int32)

    if T == 1:
        kernel = jax.jit(functools.partial(
            paged_attention_decode, block_size=bs))

        def pallas_path(q, kc, vc, tables, lens):
            return kernel(q[:, 0], kc, vc, tables, lens)[:, None]
    else:
        q_start = jnp.arange(B + 1, dtype=jnp.int32) * T
        q_lens = jnp.full((B,), T, jnp.int32)
        kernel = jax.jit(functools.partial(
            paged_attention_ragged, block_size=bs, max_q_len=T))

        def pallas_path(q, kc, vc, tables, lens):
            out = kernel(q.reshape(B * T, H, hd), kc, vc, tables,
                         q_start, q_lens, lens)
            return out.reshape(B, T, H, hd)

    @jax.jit
    def einsum_path(q, kc, vc, tables, lens):
        k_all = jnp.take(kc, tables.reshape(-1), axis=0).reshape(
            B, W, KV, bs, hd
        ).transpose(0, 1, 3, 2, 4).reshape(B, W * bs, KV, hd)
        v_all = jnp.take(vc, tables.reshape(-1), axis=0).reshape(
            B, W, KV, bs, hd
        ).transpose(0, 1, 3, 2, 4).reshape(B, W * bs, KV, hd)
        pos = (lens[:, None] - T) + jnp.arange(T)[None, :]
        return model_lib._attention(q, k_all, v_all, pos)

    args = (q, k, v, tables, lens)
    pallas_ms = _time_attention(jax.jit(pallas_path), args)
    einsum_ms = _time_attention(einsum_path, args)
    return {
        "impl": "pallas" if pallas_ms < einsum_ms else "einsum",
        "B": B, "T": T,
        "pallas_ms": round(pallas_ms, 4),
        "einsum_ms": round(einsum_ms, 4),
        # >1 means the Pallas kernel is faster
        "ratio": round(einsum_ms / max(pallas_ms, 1e-9), 3),
    }


def probe_attention_impl(
    model_config: ModelConfig, engine_config: EngineConfig,
) -> Tuple[EngineConfig, dict]:
    """Resolve ``attention_impl="auto"`` → concrete per-class impls.

    Returns (engine_config with the winners substituted — ``attention_impl``
    carries the decode winner for back-compat and each
    ``attention_impl_{decode,spec,prefill}`` its class winner — plus a
    choice-info dict with the per-class times and ratios under "classes").
    Anything going wrong in a probe falls back to einsum — the
    always-correct reference path.
    """
    import jax

    if engine_config.attention_impl != "auto":
        return engine_config, {
            "impl": engine_config.attention_impl, "probed": False,
        }

    choice: dict = {"probed": False, "classes": {}}
    impls = {"decode": "einsum", "spec": "einsum", "prefill": "einsum"}
    if jax.default_backend() != "tpu":
        # interpret-mode Pallas is not a contender; don't burn startup time
        choice.update(impl="einsum", reason="non-tpu backend")
    else:
        B_dec = min(16, max(engine_config.decode_buckets))
        shapes = {"decode": (B_dec, 1)}
        if engine_config.spec_mode != "off":
            shapes["spec"] = (B_dec, engine_config.spec_k + 1)
        shapes["prefill"] = (4, min(256, max(engine_config.prefill_buckets)))
        for cls, (B, T) in shapes.items():
            try:
                info = _probe_class(model_config, engine_config, B, T)
                impls[cls] = info["impl"]
                choice["classes"][cls] = info
                choice["probed"] = True
            except Exception as e:
                choice["classes"][cls] = {
                    "impl": "einsum",
                    "reason": f"probe failed: {type(e).__name__}: {e}",
                }
        choice["impl"] = impls["decode"]
        # legacy top-level fields mirror the decode class (bench back-compat)
        dec = choice["classes"].get("decode", {})
        for key in ("pallas_ms", "einsum_ms", "ratio"):
            if key in dec:
                choice[key] = dec[key]
    log.info("attention_impl=auto resolved: %s", choice)
    resolved = dataclasses.replace(
        engine_config,
        attention_impl=choice.get("impl", "einsum"),
        attention_impl_decode=impls["decode"],
        attention_impl_spec=impls["spec"],
        attention_impl_prefill=impls["prefill"],
    )
    return resolved, choice


# ---------------------------------------------------------------------------
# tile sweep: candidate grid, parity gate, timing
# ---------------------------------------------------------------------------


def _sublane(dtype: str) -> int:
    return _SUBLANE.get(dtype, 8)


def class_shapes(
    model_config: ModelConfig, engine_config: EngineConfig,
) -> Dict[str, Tuple[int, int]]:
    """Representative ``(B, T)`` per shape class (the probe/sweep shapes)."""
    B_dec = min(16, max(engine_config.decode_buckets))
    shapes = {"decode": (B_dec, 1)}
    if engine_config.spec_mode != "off":
        shapes["spec"] = (B_dec, engine_config.spec_k + 1)
    shapes["prefill"] = (4, min(256, max(engine_config.prefill_buckets)))
    return shapes


def tile_candidates(
    model_config: ModelConfig, engine_config: EngineConfig,
    attn_class: str, T: int,
) -> List[Tuple[int, int]]:
    """The ``(q_tile, kv_tile)`` grid swept for one shape class.

    ``(0, 0)`` — the kernel default — is always first and always eligible,
    so the sweep can only ever match or beat the default.  q_tile must
    divide the class's query window T (decode: always 1); kv_tile must
    divide ``block_size`` and respect the dtype's minimum sublane tile
    (f32: 8, bf16: 16) since it is the second-to-minor axis of the K/V
    block DMA.
    """
    from . import quant

    bs = engine_config.block_size
    # the K/V page DMA's sublane floor follows the *storage* dtype: the
    # model dtype for bf16 passthrough, the 1-byte tile for quantized KV
    page_dtype = engine_config.kv_dtype \
        if quant.is_quantized(engine_config.kv_dtype) else model_config.dtype
    sub = _sublane(page_dtype)
    kv_tiles = [0] + [
        kt for kt in (8, 16, 32, 64, 128)
        if kt >= sub and kt < bs and bs % kt == 0
    ]
    if attn_class == "decode":
        q_tiles = [0]
    else:
        default_qt = min(T, 128) if T % min(T, 128) == 0 else T
        q_tiles = [0] + [
            qt for qt in (1, 2, 4, 8, 16, 32, 64, 128)
            if qt != default_qt and qt < T and T % qt == 0
        ]
    return [(qt, kt) for qt in q_tiles for kt in kv_tiles]


def make_sweep_case(
    model_config: ModelConfig, engine_config: EngineConfig,
    attn_class: str, B: int, T: int, *,
    W: int = 0, seed: int = 0, poison: bool = True,
) -> dict:
    """A mixed ragged batch for one shape class's parity/timing runs.

    Rows pack with stride T (the engine layout).  Occupancy is
    deliberately ragged: full rows, a short-context row, a partial-q row
    (spec/prefill), a dead seat whose table is all trash (block 0), and —
    with ``poison`` — NaN bits in the trash block and every partial block
    tail, so a tile candidate that mis-masks can never pass the gate.

    With a quantized ``engine_config.kv_dtype`` the caches are quantized
    per (slot, head) and the case carries the parallel ``k_scale`` /
    ``v_scale`` arrays; poisoning then NaNs the *scales* of trash/tail
    slots (and the fp8 payload, which can encode NaN) — a candidate that
    dequantizes a masked slot before zeroing it still fails the gate.
    """
    from . import quant

    bs = engine_config.block_size
    W = W or max(2, min(8, engine_config.max_blocks_per_seq))
    KV = model_config.num_kv_heads
    H = model_config.num_heads
    hd = model_config.head_dim_
    rng = np.random.default_rng(seed)
    dt = np.dtype("float32") if model_config.dtype != "bfloat16" else None

    rows = []  # (q_len, ctx_len)
    full_ctx = W * bs
    for b in range(B):
        mode = b % 4
        if mode == 0:
            rows.append((T, full_ctx))               # steady state
        elif mode == 1:
            rows.append((T, T + (bs // 2)))          # short ctx, partial tail
        elif mode == 2:
            rows.append((max(1, T // 2), full_ctx - 3))  # partial q window
        else:
            rows.append((0, 0))                      # dead seat / all trash
    nb = 1 + sum((cl + bs - 1) // bs for _, cl in rows)
    q = rng.standard_normal((B * T, H, hd)).astype(np.float32)
    k_cache = rng.standard_normal((nb, KV, bs, hd)).astype(np.float32)
    v_cache = rng.standard_normal((nb, KV, bs, hd)).astype(np.float32)
    tables = np.zeros((B, W), np.int32)
    nxt = 1
    poison_slots = []  # (block, first poisoned slot offset)
    for r, (ql, cl) in enumerate(rows):
        for w in range((cl + bs - 1) // bs):
            tables[r, w] = nxt
            nxt += 1
        if poison and cl % bs:
            poison_slots.append((int(tables[r, cl // bs]), cl % bs))
    if poison:
        poison_slots.append((0, 0))  # the trash block, wholesale

    kv_dtype = engine_config.kv_dtype
    quantized = quant.is_quantized(kv_dtype)
    k_scale = v_scale = None
    if quantized:
        # quantize the clean values first, then poison the quantized form
        k_cache, k_scale = quant.kv_quantize_cache_np(k_cache, kv_dtype)
        v_cache, v_scale = quant.kv_quantize_cache_np(v_cache, kv_dtype)
    for blk, off in poison_slots:
        if quantized:
            k_scale[blk, :, off:] = np.nan
            v_scale[blk, :, off:] = np.nan
            if kv_dtype == "fp8":  # e4m3fn encodes NaN; int8 cannot
                k_cache[blk, :, off:] = np.nan
                v_cache[blk, :, off:] = np.nan
        else:
            k_cache[blk, :, off:] = np.nan
            v_cache[blk, :, off:] = np.nan
    if dt is None:
        import jax.numpy as jnp
        q = np.asarray(jnp.asarray(q, jnp.bfloat16))
        if not quantized:
            k_cache = np.asarray(jnp.asarray(k_cache, jnp.bfloat16))
            v_cache = np.asarray(jnp.asarray(v_cache, jnp.bfloat16))
    return {
        "attn_class": attn_class,
        "args": (
            q, k_cache, v_cache, tables,
            np.arange(B + 1, dtype=np.int32) * T,
            np.asarray([r[0] for r in rows], np.int32),
            np.asarray([r[1] for r in rows], np.int32),
        ),
        "k_scale": k_scale,
        "v_scale": v_scale,
        "kv_dtype": kv_dtype,
        "block_size": bs,
        "max_q_len": T,
    }


def reference_ragged(
    q, k_cache, v_cache, tables, q_start, q_len, ctx_len, *,
    block_size: int, max_q_len: int, q_tile: int = 0, kv_tile: int = 0,
    k_scale=None, v_scale=None,
) -> np.ndarray:
    """Order-exact reference for one ``(q_tile, kv_tile)`` candidate.

    Replays the kernel's per-(row, q-tile, kv-step) online-softmax
    recurrence with the same ops, shapes, and reduction order through
    plain jnp — so an interpret-mode run of the candidate must agree
    **bit-for-bit** (assert with ``np.array_equal``; run both under
    ``XLA_FLAGS=--xla_disable_hlo_passes=fusion`` so XLA cannot re-fuse
    one side differently).  Different tile configs produce different —
    individually exact — references: tiling changes the accumulation
    order, which is precisely what this pins down.  Use a naive softmax
    (``reference_naive``) as the everything-independent correctness
    anchor under tolerance.
    """
    import jax
    import jax.numpy as jnp

    Tq, H, hd = q.shape
    KV = k_cache.shape[1]
    G = H // KV
    R, W = tables.shape
    bs = block_size
    if q_tile <= 0:
        q_tile = min(max_q_len, 128) if max_q_len % min(max_q_len, 128) == 0 \
            else max_q_len
    if kv_tile <= 0:
        kv_tile = bs
    splits = bs // kv_tile
    scale = 1.0 / (hd ** 0.5)
    q4 = jnp.asarray(q).reshape(Tq, KV, G, hd).transpose(1, 0, 2, 3)
    kc = jnp.asarray(k_cache)
    vc = jnp.asarray(v_cache)
    ks = jnp.asarray(k_scale) if k_scale is not None else None
    vs = jnp.asarray(v_scale) if v_scale is not None else None
    out = np.zeros((KV, Tq, G, hd), np.asarray(q).dtype)
    for r in range(R):
        qs, qe = int(q_start[r]), int(q_start[r + 1])
        ql, cl = int(q_len[r]), int(ctx_len[r])
        for t in range((qe - qs) // q_tile):
            live = t * q_tile < ql
            last_q = min((t + 1) * q_tile, ql) - 1
            max_vis = cl - ql + last_q
            m = jnp.full((KV, q_tile * G, 1), -jnp.inf, jnp.float32)
            l = jnp.zeros((KV, q_tile * G, 1), jnp.float32)
            acc = jnp.zeros((KV, q_tile * G, hd), jnp.float32)
            for w in range(W * splits):
                if not (live and w * kv_tile <= max_vis):
                    continue
                qf = q4[:, qs + t * q_tile: qs + (t + 1) * q_tile]
                qf = qf.astype(jnp.float32).reshape(KV, q_tile * G, hd)
                blk = int(tables[r, w // splits])
                sl = slice((w % splits) * kv_tile,
                           (w % splits + 1) * kv_tile)
                k = kc[blk][:, sl].astype(jnp.float32)
                v = vc[blk][:, sl].astype(jnp.float32)
                if ks is not None:
                    # same op order as the kernel: dequantize, THEN the
                    # kvalid zeroing wipes trash/tail bits (NaN scales incl.)
                    k = k * ks[blk][:, sl].astype(jnp.float32)[..., None]
                    v = v * vs[blk][:, sl].astype(jnp.float32)[..., None]
                kpos = w * kv_tile + jax.lax.broadcasted_iota(
                    jnp.int32, (1, kv_tile, 1), 1)
                kvalid = kpos < cl
                k = jnp.where(kvalid, k, 0.0)
                v = jnp.where(kvalid, v, 0.0)
                s = jax.lax.dot_general(
                    qf, k, (((2,), (2,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32,
                ) * scale
                qi = t * q_tile + jax.lax.broadcasted_iota(
                    jnp.int32, s.shape, 1) // G
                spos = w * kv_tile + jax.lax.broadcasted_iota(
                    jnp.int32, s.shape, 2)
                s = jnp.where((qi < ql) & (spos <= cl - ql + qi), s,
                              -jnp.inf)
                m_cur = jnp.max(s, axis=-1, keepdims=True)
                m_new = jnp.maximum(m, m_cur)
                m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
                alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe,
                                          -jnp.inf))
                p = jnp.exp(s - m_safe)
                m = m_new
                l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
                acc = acc * alpha + jax.lax.dot_general(
                    p, v, (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32,
                )
            o = acc / jnp.where(l == 0.0, 1.0, l)
            out[:, qs + t * q_tile: qs + (t + 1) * q_tile] = np.asarray(
                o.reshape(KV, q_tile, G, hd).astype(q4.dtype))
    return out.transpose(1, 0, 2, 3).reshape(Tq, H, hd)


def reference_naive(
    q, k_cache, v_cache, tables, q_start, q_len, ctx_len, *,
    block_size: int,
) -> np.ndarray:
    """Naive numpy softmax over the gathered context (float64 accumulate).

    The tile-order-independent correctness anchor: every candidate must
    stay within tolerance of this, on top of the bitwise match against its
    own ``reference_ragged``.  NaN-poisoned cache slots are zeroed first —
    positions past ``ctx_len`` are masked anyway, the kernel contract says
    their bits never matter.
    """
    q = np.nan_to_num(np.asarray(q, np.float64))
    kc = np.nan_to_num(np.asarray(k_cache, np.float64))
    vc = np.nan_to_num(np.asarray(v_cache, np.float64))
    Tq, H, hd = q.shape
    KV = kc.shape[1]
    G = H // KV
    R, W = tables.shape
    bs = block_size
    scale = 1.0 / (hd ** 0.5)
    out = np.zeros((Tq, H, hd), np.float64)
    for r in range(R):
        qs = int(q_start[r])
        ql, cl = int(q_len[r]), int(ctx_len[r])
        if ql == 0:
            continue
        ctx_k = np.concatenate(
            [kc[tables[r, w]] for w in range((cl + bs - 1) // bs)] or
            [np.zeros((KV, 0, hd))], axis=1)[:, :cl]      # [KV, cl, hd]
        ctx_v = np.concatenate(
            [vc[tables[r, w]] for w in range((cl + bs - 1) // bs)] or
            [np.zeros((KV, 0, hd))], axis=1)[:, :cl]
        for i in range(ql):
            pos = cl - ql + i
            for h in range(H):
                kv = h // G
                s = ctx_k[kv, :pos + 1] @ q[qs + i, h] * scale
                p = np.exp(s - s.max())
                out[qs + i, h] = (p / p.sum()) @ ctx_v[kv, :pos + 1]
    return out


def parity_check(
    case: dict, q_tile: int, kv_tile: int, *, tol: float = 2e-3,
) -> dict:
    """Run one candidate in interpret mode and gate it against references.

    Returns ``{"bitwise": ..., "max_err_exact": ..., "max_err_naive": ...,
    "eligible": ...}``.  ``bitwise`` requires the fusion pass disabled
    (see ``reference_ragged``); ``eligible`` additionally demands the
    naive-softmax anchor within ``tol`` and a NaN-free output.
    """
    import jax.numpy as jnp

    from ..ops.paged_attention import paged_attention_ragged
    from . import quant

    q, kc, vc, tables, q_start, q_len, ctx_len = case["args"]
    ks, vs = case.get("k_scale"), case.get("v_scale")
    out = np.asarray(paged_attention_ragged(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(tables), jnp.asarray(q_start), jnp.asarray(q_len),
        jnp.asarray(ctx_len),
        block_size=case["block_size"], max_q_len=case["max_q_len"],
        q_tile=q_tile, kv_tile=kv_tile, interpret=True,
        k_scale=None if ks is None else jnp.asarray(ks),
        v_scale=None if vs is None else jnp.asarray(vs),
    ))
    exact = reference_ragged(
        q, kc, vc, tables, q_start, q_len, ctx_len,
        block_size=case["block_size"], max_q_len=case["max_q_len"],
        q_tile=q_tile, kv_tile=kv_tile, k_scale=ks, v_scale=vs,
    )
    if ks is not None:
        # anchor on the dequantized caches: quantization error is shared
        # by kernel and anchor, leaving only accumulation-order noise
        kc = quant.kv_dequantize_cache_np(kc, ks)
        vc = quant.kv_dequantize_cache_np(vc, vs)
    naive = reference_naive(
        q, kc, vc, tables, q_start, q_len, ctx_len,
        block_size=case["block_size"],
    )
    finite = bool(np.isfinite(out.astype(np.float32)).all())
    bitwise = bool(np.array_equal(out, exact))
    err_exact = float(np.max(np.abs(
        out.astype(np.float64) - exact.astype(np.float64)), initial=0.0))
    # only valid slots count against the naive anchor (slots past q_len
    # are exact zeros by contract, the naive reference skips them)
    mask = np.zeros(out.shape[0], bool)
    for r in range(len(q_len)):
        mask[int(q_start[r]): int(q_start[r]) + int(q_len[r])] = True
    err_naive = float(np.max(np.abs(
        out.astype(np.float64)[mask] - naive[mask]), initial=0.0))
    return {
        "q_tile": q_tile, "kv_tile": kv_tile,
        "bitwise": bitwise, "finite": finite,
        "max_err_exact": err_exact, "max_err_naive": err_naive,
        "eligible": bool(bitwise and finite and err_naive <= tol),
    }


def sweep_class_parity(
    model_config: ModelConfig, engine_config: EngineConfig,
    attn_class: str, *, B: int = 0, T: int = 0, seed: int = 0,
) -> List[dict]:
    """CPU parity sweep: every candidate of one class through the gate."""
    shapes = class_shapes(model_config, engine_config)
    B0, T0 = shapes.get(attn_class, shapes["prefill"])
    B, T = B or B0, T or T0
    case = make_sweep_case(
        model_config, engine_config, attn_class, B, T, seed=seed)
    return [
        parity_check(case, qt, kt)
        for qt, kt in tile_candidates(
            model_config, engine_config, attn_class, T)
    ]


def _ragged_scaled(q, kc, vc, tables, q_start, q_len, ctx_len,
                   k_scale, v_scale, **kw):
    """Positional-scales wrapper so the timing loop's ``fn(*args)`` shape
    works for both passthrough and quantized-KV candidates."""
    from ..ops.paged_attention import paged_attention_ragged

    return paged_attention_ragged(
        q, kc, vc, tables, q_start, q_len, ctx_len,
        k_scale=k_scale, v_scale=v_scale, **kw)


def _sweep_class_device(
    model_config: ModelConfig, engine_config: EngineConfig,
    attn_class: str, B: int, T: int,
) -> dict:
    """Time every candidate on the live backend; pick the fastest eligible.

    Eligibility at runtime is numeric — each candidate must match the
    gathered-einsum path within dtype tolerance on a clean (non-poisoned)
    mixed ragged case.  W (the decode-window block-table width) is part of
    the swept shape: candidates are timed at a shallow and a deep table
    and scored on the sum, so a winner can't overfit one context depth.
    """
    import jax
    import jax.numpy as jnp

    from ..ops.paged_attention import paged_attention_ragged
    from . import quant

    bs = engine_config.block_size
    cap = engine_config.max_blocks_per_seq
    widths = sorted({max(2, min(8, cap)), max(2, min(32, cap))})
    tol = 2e-2 if model_config.dtype == "bfloat16" else 2e-3
    if quant.is_quantized(engine_config.kv_dtype):
        tol = max(tol, 5e-2)  # quantization error rides the same anchor
    results: List[dict] = []
    for q_tile, kv_tile in tile_candidates(
            model_config, engine_config, attn_class, T):
        entry = {"q_tile": q_tile, "kv_tile": kv_tile, "ms": {},
                 "eligible": True}
        total = 0.0
        for W in widths:
            case = make_sweep_case(
                model_config, engine_config, attn_class, B, T,
                W=W, poison=False)
            q, kc, vc, tables, q_start, q_len, ctx_len = (
                jnp.asarray(a) for a in case["args"])
            ks_np, vs_np = case.get("k_scale"), case.get("v_scale")
            # one throwaway wrapper per candidate BY DESIGN: each (q_tile,
            # kv_tile) is a distinct static config, so no cache is shared
            # and this cold startup sweep never runs in the serving loop
            fn = jax.jit(functools.partial(  # dynalint: disable=DT203
                paged_attention_ragged if ks_np is None else _ragged_scaled,
                block_size=bs, max_q_len=T,
                q_tile=q_tile, kv_tile=kv_tile,
            ))
            args = (q, kc, vc, tables, q_start, q_len, ctx_len)
            if ks_np is not None:
                args = args + (jnp.asarray(ks_np), jnp.asarray(vs_np))
            try:
                out = np.asarray(fn(*args))
                kc_h, vc_h = np.asarray(kc), np.asarray(vc)
                if ks_np is not None:
                    kc_h = quant.kv_dequantize_cache_np(kc_h, ks_np)
                    vc_h = quant.kv_dequantize_cache_np(vc_h, vs_np)
                ref = np.asarray(reference_naive(
                    np.asarray(q), kc_h, vc_h, np.asarray(tables),
                    np.asarray(q_start), np.asarray(q_len),
                    np.asarray(ctx_len), block_size=bs))
                mask = np.zeros(out.shape[0], bool)
                ql_h = np.asarray(q_len)
                qs_h = np.asarray(q_start)
                for r in range(len(ql_h)):
                    mask[int(qs_h[r]): int(qs_h[r]) + int(ql_h[r])] = True
                err = float(np.max(np.abs(
                    out.astype(np.float64)[mask] - ref[mask]), initial=0.0))
                if not np.isfinite(out.astype(np.float32)).all() \
                        or err > tol:
                    entry["eligible"] = False
                    entry["reason"] = f"numeric gate failed (err {err:.2e})"
                    break
                ms = _time_attention(fn, args)
                entry["ms"][f"W{W}"] = round(ms, 4)
                total += ms
            except Exception as e:  # Mosaic may reject a tile shape
                entry["eligible"] = False
                entry["reason"] = f"{type(e).__name__}: {e}"
                break
        entry["total_ms"] = round(total, 4)
        results.append(entry)
    eligible = [e for e in results if e["eligible"]]
    winner = min(eligible, key=lambda e: e["total_ms"]) if eligible \
        else results[0]
    return {
        "B": B, "T": T, "widths": widths,
        "winner": (winner["q_tile"], winner["kv_tile"]),
        "candidates": results,
    }


# ---------------------------------------------------------------------------
# persisted tuning cache
# ---------------------------------------------------------------------------


def config_hash(
    model_config: ModelConfig, engine_config: EngineConfig,
    device_kind: str,
) -> str:
    """Cache key: shape-relevant config + device + jax version.

    Any drift in what the sweep actually measured — model geometry, cache
    layout, bucket grids, spec window, device generation, jax release —
    changes the key, so a stale winner can never be replayed; unknown keys
    fall back to kernel defaults.
    """
    import jax

    key = {
        "model": dataclasses.asdict(model_config),
        "engine": {
            "block_size": engine_config.block_size,
            "decode_buckets": list(engine_config.decode_buckets),
            "prefill_buckets": list(engine_config.prefill_buckets),
            "spec_mode": engine_config.spec_mode,
            "spec_k": engine_config.spec_k,
            "max_model_len": engine_config.max_model_len,
            "max_num_seqs": engine_config.max_num_seqs,
            "mesh_shape": list(engine_config.mesh_shape),
            # storage dtype changes the K/V DMA tile economics, so quant
            # winners never leak into bf16 runs (or vice versa)
            "kv_dtype": engine_config.kv_dtype,
        },
        "device_kind": device_kind,
        "jax": jax.__version__,
        "cache_version": CACHE_VERSION,
    }
    blob = json.dumps(key, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def cache_path() -> Optional[str]:
    return os.environ.get(CACHE_ENV) or None


def load_cache_entry(path: str, key: str) -> Optional[dict]:
    """The persisted entry for ``key``, or None on miss/drift/corruption."""
    try:
        with open(path) as f:
            doc = json.load(f)
        if doc.get("version") != CACHE_VERSION:
            return None
        entry = doc.get("entries", {}).get(key)
        if not isinstance(entry, dict) or "tiles" not in entry:
            return None
        return entry
    except (OSError, ValueError):
        return None


def store_cache_entry(path: str, key: str, entry: dict) -> bool:
    """Merge ``entry`` under ``key``; atomic rename, best-effort."""
    doc: dict = {"version": CACHE_VERSION, "entries": {}}
    try:
        with open(path) as f:
            old = json.load(f)
        if old.get("version") == CACHE_VERSION:
            doc = old
    except (OSError, ValueError):
        pass
    doc.setdefault("entries", {})[key] = entry
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return True
    except OSError as e:
        log.warning("autotune cache write failed (%s): %s", path, e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


# ---------------------------------------------------------------------------
# top-level: impl probe + tile resolution (what the engine calls)
# ---------------------------------------------------------------------------


def autotune_attention(
    model_config: ModelConfig, engine_config: EngineConfig,
) -> Tuple[EngineConfig, dict]:
    """Impl probe + per-class tile resolution, cache-backed.

    Order of precedence per class: explicit ``attention_tile_{class}`` in
    the config > persisted cache hit (``DYNTPU_AUTOTUNE_CACHE``) > on-TPU
    sweep (winners stored back) > kernel defaults.  The returned choice
    dict always carries ``autotune_cache_hit``, ``config_hash`` and the
    resolved ``tiles`` so bench/serving can report what actually ran.
    """
    import jax

    from ..utils.config import env_flag
    from . import model as model_lib

    cfg, choice = probe_attention_impl(model_config, engine_config)
    choice = dict(choice)
    try:
        device_kind = jax.devices()[0].device_kind
    except Exception:
        device_kind = jax.default_backend()
    key = config_hash(model_config, cfg, device_kind)
    path = cache_path()
    choice.update(autotune_cache_hit=False, config_hash=key,
                  cache_path=path or "")

    tiles: Dict[str, Tuple[int, int]] = {
        cls: (0, 0) for cls in ("decode", "spec", "prefill")}
    pallas_classes = [
        cls for cls in tiles
        if model_lib.resolve_attention_impl(cfg, cls) == "pallas"
    ]

    entry = load_cache_entry(path, key) if path else None
    if entry is not None:
        for cls, t in entry.get("tiles", {}).items():
            if cls in tiles and len(t) == 2:
                tiles[cls] = (int(t[0]), int(t[1]))
        choice["autotune_cache_hit"] = True
        choice["sweep"] = entry.get("sweep", {})
    elif (jax.default_backend() == "tpu" and pallas_classes
          and env_flag(SWEEP_ENV, True)):
        sweep: Dict[str, dict] = {}
        shapes = class_shapes(model_config, cfg)
        for cls in pallas_classes:
            B, T = shapes.get(cls, shapes["prefill"])
            try:
                res = _sweep_class_device(model_config, cfg, cls, B, T)
                tiles[cls] = tuple(res["winner"])
                sweep[cls] = res
            except Exception as e:
                log.warning("tile sweep failed for %s: %s", cls, e)
                sweep[cls] = {"error": f"{type(e).__name__}: {e}"}
        choice["sweep"] = sweep
        if path and sweep:
            store_cache_entry(path, key, {
                "device_kind": device_kind,
                "tiles": {cls: list(t) for cls, t in tiles.items()},
                "sweep": sweep,
            })

    # explicit config tiles always win over cache/sweep
    for cls in tiles:
        explicit = getattr(engine_config, f"attention_tile_{cls}")
        if tuple(explicit) != (0, 0):
            tiles[cls] = tuple(explicit)
    choice["tiles"] = {cls: list(t) for cls, t in tiles.items()}
    resolved = dataclasses.replace(
        cfg,
        attention_tile_decode=tiles["decode"],
        attention_tile_spec=tiles["spec"],
        attention_tile_prefill=tiles["prefill"],
    )
    return resolved, choice


# ---------------------------------------------------------------------------
# CPU parity selftest (scripts/verify.sh tune drives this in a subprocess
# with XLA_FLAGS=--xla_disable_hlo_passes=fusion, see reference_ragged)
# ---------------------------------------------------------------------------


def parity_selftest(seed: int = 0, kv_dtype: str = "bf16") -> dict:
    """Every candidate of every class through the bitwise gate on CPU."""
    model_config = ModelConfig.tiny()
    engine_config = EngineConfig(
        block_size=16, num_blocks=128, max_num_seqs=8,
        max_num_batched_tokens=256, max_model_len=256,
        decode_buckets=(8,), prefill_buckets=(16, 32),
        spec_mode="ngram", spec_k=3, kv_dtype=kv_dtype,
    )
    report: dict = {
        "fusion_disabled": "--xla_disable_hlo_passes=fusion"
        in os.environ.get("XLA_FLAGS", ""),
        "classes": {}, "all_eligible": True,
    }
    for cls in ("decode", "spec", "prefill"):
        rows = sweep_class_parity(
            model_config, engine_config, cls, seed=seed)
        report["classes"][cls] = rows
        if not all(r["eligible"] for r in rows):
            report["all_eligible"] = False
    return report


if __name__ == "__main__":
    print(json.dumps(parity_selftest(), indent=1))
