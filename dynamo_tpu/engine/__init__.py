"""The TPU-native model engine.

The piece the reference outsources to vLLM/SGLang/TRT-LLM (ref: components/
backends/vllm/src/dynamo/vllm/main.py:68 builds ``AsyncLLM``); this framework
owns it. A JAX/XLA Llama-class model with a paged, HBM-resident KV cache, a
continuous-batching scheduler with chunked prefill and prefix caching, and an
asyncio engine loop that streams tokens per request while emitting KV events
and forward-pass metrics for the router.
"""

from .config import EngineConfig, ModelConfig
from .engine import InferenceEngine, Request, StepOutput

__all__ = [
    "EngineConfig",
    "ModelConfig",
    "InferenceEngine",
    "Request",
    "StepOutput",
]
