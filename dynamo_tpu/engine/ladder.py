"""Waste-driven adaptive bucket ladders (ROADMAP item 2).

Static ``decode_buckets``/``prefill_buckets`` trade recompiles for padding:
every dispatch pads its batch (rows) or chunk (tokens) up to the nearest
bucket, and the flight recorder books the pad as ``padding_waste_ratio`` —
pure MFU loss.  A :class:`BucketLadder` closes that loop: it consumes the
recorder's live per-(kind, bucket) occupancy histogram
(``StepStats.bucket_occupancy()``) and, at adaptation epochs,

- **splits** the rung wasting the most padded work (inserting a new rung
  at the observed mean fill, rounded to ``step``), and
- **retires** rungs that have gone cold (dispatch share below
  ``retire_share`` for ``hysteresis`` consecutive epochs),

under an explicit **compile budget**: each added rung costs exactly one
steady-state XLA trace per jit family that consumes it (the compile
watchdog attributes it by label), and the ladder will never add more than
``compile_budget`` rungs over its lifetime.  Hysteresis applies on both
edges — a just-added rung cannot be retired, and a just-retired value
cannot be re-added, for ``hysteresis`` epochs — so the grid converges and
``compilewatch.assert_no_recompiles`` holds once it has.

The ladder is pure host bookkeeping over host ints (never touches device
state), deterministic given an occupancy trace, and disabled by default
(``EngineConfig.adaptive_buckets`` / ``DYNTPU_LADDER_ENABLED``).

Env knobs (all ``DYNTPU_LADDER_*``) override the constructor defaults:
``ENABLED``, ``COMPILE_BUDGET``, ``SPLIT_WASTE``, ``RETIRE_SHARE``,
``MIN_DISPATCHES``, ``HYSTERESIS``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.config import env_float, env_int
from ..utils.logging import get_logger

log = get_logger("ladder")

ENV_PREFIX = "DYNTPU_LADDER_"


class BucketLadder:
    """Adaptive bucket grid for one dispatch kind (decode or prefill).

    ``kinds`` lists the StepRecord kinds whose occupancy feeds this ladder
    (decode consumes both ``decode`` and ``spec_verify`` windows).  The
    largest base rung is permanent — it is the capacity guarantee that
    every batch/chunk has a bucket to land in.
    """

    def __init__(
        self,
        kind: str,
        base_buckets: Sequence[int],
        *,
        kinds: Optional[Sequence[str]] = None,
        compile_budget: int = 4,
        split_waste: float = 0.25,
        retire_share: float = 0.02,
        min_dispatches: int = 64,
        hysteresis: int = 2,
        step: int = 8,
    ):
        self.kind = kind
        self.kinds = tuple(kinds or (kind,))
        self._base = tuple(sorted(set(int(b) for b in base_buckets)))
        if not self._base:
            raise ValueError("need at least one base bucket")
        self._rungs: List[int] = list(self._base)
        self.compile_budget = env_int(
            ENV_PREFIX + "COMPILE_BUDGET", compile_budget)
        self.split_waste = env_float(
            ENV_PREFIX + "SPLIT_WASTE", split_waste)
        self.retire_share = env_float(
            ENV_PREFIX + "RETIRE_SHARE", retire_share)
        self.min_dispatches = env_int(
            ENV_PREFIX + "MIN_DISPATCHES", min_dispatches)
        self.hysteresis = max(1, env_int(
            ENV_PREFIX + "HYSTERESIS", hysteresis))
        self.step = max(1, step)
        self._epoch = 0
        self._splits_total = 0
        self._retires_total = 0
        self._last_event_epoch = -1
        # rung -> epoch it was added / value -> epoch it was retired
        self._added_epoch: Dict[int, int] = {}
        self._retired_epoch: Dict[int, int] = {}
        # rung -> consecutive cold epochs (resets when it sees traffic)
        self._cold_epochs: Dict[int, int] = {}
        # recorder cumulative histogram high-water (per histogram key)
        self._seen: Dict[str, Tuple[int, int, int]] = {}
        # current epoch accumulation: rung -> [dispatches, real, padded]
        self._acc: Dict[int, List[int]] = {}
        self._events: List[dict] = []

    # -- grid queries (the engine's bucketing calls) --

    def buckets(self) -> Tuple[int, ...]:
        return tuple(self._rungs)

    def bucket_for(self, n: int) -> int:
        """First rung >= n, else the largest (mirror of engine._bucket)."""
        for b in self._rungs:
            if n <= b:
                return b
        return self._rungs[-1]

    def rung_at_most(self, cap: int) -> Optional[int]:
        """Largest rung <= cap (the scheduler's chunk-cap snap), or None."""
        best = None
        for b in self._rungs:
            if b <= cap:
                best = b
        return best

    # -- occupancy intake --

    def observe(self, bucket: int, real: int, padded: int,
                count: int = 1) -> None:
        """Direct accumulation (tests / recorder-less callers)."""
        acc = self._acc.setdefault(int(bucket), [0, 0, 0])
        acc[0] += count
        acc[1] += int(real)
        acc[2] += int(padded)

    def ingest(self, occupancy: Dict[str, Sequence[int]]) -> None:
        """Fold the recorder's cumulative per-(kind, bucket) histogram in.

        Keys are ``"kind:bucket"`` -> ``(dispatches, real, padded)``
        cumulative since warmup; this takes deltas against the last call.
        A counter that went backwards means the recorder's window was
        reset (``mark_warmup_done``) — re-baseline and skip one cycle.
        """
        for key, vals in occupancy.items():
            kind, _, b = key.partition(":")
            if kind not in self.kinds:
                continue
            n, real, padded = (int(v) for v in vals)
            prev = self._seen.get(key, (0, 0, 0))
            self._seen[key] = (n, real, padded)
            dn, dr, dp = n - prev[0], real - prev[1], padded - prev[2]
            if dn <= 0 or dr < 0 or dp < 0:
                continue
            self.observe(int(b), dr, dp, count=dn)

    # -- adaptation --

    def _try_split(self, stats: Dict[int, List[int]]) -> Optional[dict]:
        if self._splits_total >= self.compile_budget:
            return None
        # rank by absolute padded waste (padded - real units): the rung
        # burning the most FLOPs on pad is the one worth a new program
        ranked = sorted(
            ((p - r, b) for b, (n, r, p) in stats.items() if p > 0),
            reverse=True,
        )
        for waste_units, b in ranked:
            n, real, padded = stats[b]
            waste = 1.0 - real / padded
            if waste <= self.split_waste:
                continue
            if b not in self._rungs:
                continue  # rung already retired under us
            lower = max((x for x in self._rungs if x < b), default=0)
            mean_real = real / n
            mid = -(-int(mean_real) // self.step) * self.step
            mid = max(mid, self.step)
            if not (lower < mid < b):
                continue  # nothing to gain between the neighbours
            cooled = self._retired_epoch.get(mid)
            if cooled is not None and \
                    self._epoch - cooled < self.hysteresis:
                continue  # value was just retired — don't flap it back
            self._rungs.append(mid)
            self._rungs.sort()
            self._splits_total += 1
            self._added_epoch[mid] = self._epoch
            return {
                "op": "split", "kind": self.kind, "epoch": self._epoch,
                "rung": b, "new": mid, "waste": round(waste, 4),
                "budget_remaining":
                    self.compile_budget - self._splits_total,
            }
        return None

    def _try_retire(self, stats: Dict[int, List[int]],
                    total_n: int) -> Optional[dict]:
        # update cold streaks for every current rung
        for b in self._rungs:
            share = stats.get(b, [0, 0, 0])[0] / max(total_n, 1)
            if share < self.retire_share:
                self._cold_epochs[b] = self._cold_epochs.get(b, 0) + 1
            else:
                self._cold_epochs[b] = 0
        for b in sorted(self._rungs):
            if b == self._rungs[-1]:
                continue  # the capacity rung is permanent
            if self._cold_epochs.get(b, 0) < self.hysteresis:
                continue
            added = self._added_epoch.get(b)
            if added is not None and \
                    self._epoch - added < self.hysteresis:
                continue  # just added — give it hysteresis epochs to warm
            self._rungs.remove(b)
            self._retires_total += 1
            self._retired_epoch[b] = self._epoch
            self._cold_epochs.pop(b, None)
            return {
                "op": "retire", "kind": self.kind, "epoch": self._epoch,
                "rung": b,
            }
        return None

    def maybe_adapt(self) -> List[dict]:
        """One adaptation epoch: at most one split and one retire.

        Below ``min_dispatches`` of accumulated evidence this is a no-op
        (the epoch keeps accumulating).  Deterministic: same occupancy
        trace, same decisions.
        """
        total_n = sum(a[0] for a in self._acc.values())
        if total_n < self.min_dispatches:
            return []
        stats = {b: list(a) for b, a in self._acc.items()}
        events = []
        ev = self._try_split(stats)
        if ev:
            events.append(ev)
        ev = self._try_retire(stats, total_n)
        if ev:
            events.append(ev)
        for ev in events:
            self._events.append(ev)
            self._last_event_epoch = self._epoch
            log.info("bucket ladder %s: %s", self.kind, ev)
        self._acc.clear()
        self._epoch += 1
        return events

    # -- reporting --

    @property
    def converged(self) -> bool:
        """No event for ``hysteresis`` epochs and no split budget pressure.

        Once True under a stationary workload the grid is final: further
        ``maybe_adapt`` calls on the same distribution make no changes,
        so ``assert_no_recompiles`` holds across them.
        """
        return self._epoch - self._last_event_epoch > self.hysteresis

    def snapshot(self) -> dict:
        return {
            "rungs": tuple(self._rungs),
            "base": self._base,
            "splits_total": self._splits_total,
            "retires_total": self._retires_total,
            "compile_budget": self.compile_budget,
            "budget_remaining": self.compile_budget - self._splits_total,
            "epoch": self._epoch,
            "converged": self.converged,
        }
