"""Pallas TPU ragged paged-attention kernel.

ONE kernel serves every attention shape the engine dispatches against the
paged KV cache — following the *Ragged Paged Attention* design (PAPERS.md):

- decode rows (``q_len == 1``) — the serving hot loop,
- spec-verify windows (``q_len == k+1``, ``engine.model.raw_spec_window_fn``),
- prefill chunks (``q_len`` up to the chunk budget),

all mixed in one launch.  Queries are packed along a single flat axis; each
row ``r`` owns the slots ``[q_start[r], q_start[r+1])`` and fills the first
``q_len[r]`` of them.  The per-row ``(q_start, q_len, ctx_len)`` metadata and
the block tables ride ``PrefetchScalarGridSpec`` scalar prefetch, so the K/V
``BlockSpec`` index maps *read the block table* to pick which physical block
Mosaic DMAs next — the pipeline does the paged gather for free, double-
buffered, overlapping the previous block's FLOPs.  All KV heads of a page
travel in one ``[KV, bs, hd]`` block (one contiguous DMA, few large grid
steps — a per-(b, kv, w) grid was measured 8× slower from per-step
overheads).  Flash-style online softmax keeps nothing materialised; per-row
causal masking makes query ``i`` of row ``r`` (absolute position
``ctx_len - q_len + i``) see exactly the keys at positions ``<= that``.

Trash-block contract (physical block 0): the scheduler never allocates
block 0 and scatters every padding write into it, so its contents are
arbitrary.  The kernel guarantees that rows with ``q_len == 0`` (freshly
reset seats, padding rows) and key slots at positions ``>= ctx_len``
(partial last blocks, stale table tails) contribute *exactly zero* and can
never NaN-poison the online softmax: masked K/V is zeroed before the MXU,
masked scores go to ``-inf`` behind a finite-max guard, and a zero softmax
denominator divides as 1 — dead rows emit exact zeros.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _row_tile(t, q_start_ref, r, q_tile):
    """Clamp grid q-tile ``t`` into row ``r``'s own allotment.

    A row owns ``(q_start[r+1] - q_start[r]) // q_tile`` tiles; grid steps
    past that are no-ops but must still map somewhere — clamping keeps them
    inside the row so they can never clobber a neighbour's output block.
    """
    alloc = (q_start_ref[r + 1] - q_start_ref[r]) // q_tile
    t_eff = jnp.minimum(t, jnp.maximum(alloc - 1, 0))
    return alloc, t_eff


def _ragged_kernel(
    # scalar prefetch
    q_start_ref,   # [R+1] int32 flat q slot of each row (multiples of TQ)
    q_len_ref,     # [R] int32 valid queries per row (0 = dead row)
    ctx_len_ref,   # [R] int32 context length incl. the row's fed tokens
    tables_ref,    # [R, W] int32 physical block ids (0 = trash)
    # blocks
    q_ref,         # [KV, TQ, G, hd]
    k_ref,         # [1, KV, kv_tile, hd]
    v_ref,         # [1, KV, kv_tile, hd]
    # quantized kv_dtype adds two scale blocks here: ks_ref/vs_ref
    # [1, KV, kv_tile] f32 (see *rest unpacking below)
    *rest,
    kv_tile: int,
    q_tile: int,
    scale: float,
    quantized: bool = False,
):
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_ref, l_ref, acc_ref = rest
    r = pl.program_id(0)
    t = pl.program_id(1)
    w = pl.program_id(2)
    num_w = pl.num_programs(2)
    # grid step w covers absolute key positions [w*kv_tile, (w+1)*kv_tile):
    # when kv_tile sub-splits a physical block, consecutive w walk its
    # sub-tiles in order, so the online-softmax math below is oblivious to
    # whether a step is a whole block or a slice of one.
    bs = kv_tile

    q_len = q_len_ref[r]
    ctx_len = ctx_len_ref[r]
    alloc, t_eff = _row_tile(t, q_start_ref, r, q_tile)
    in_row = t < alloc                 # this step owns an output tile
    live = t_eff * q_tile < q_len      # ... with at least one valid query
    # highest key position any query of this tile may see
    last_q = jnp.minimum((t_eff + 1) * q_tile, q_len) - 1
    max_vis = ctx_len - q_len + last_q

    @pl.when(w == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(in_row & live & (w * bs <= max_vis))
    def _compute():
        KV, TQ, G, hd = q_ref.shape
        q = q_ref[...].astype(jnp.float32).reshape(KV, TQ * G, hd)
        k = k_ref[0].astype(jnp.float32)                 # [KV, bs, hd]
        v = v_ref[0].astype(jnp.float32)
        if ks_ref is not None:
            # quantized pages: dequantize with the per-(slot, head) scales
            # BEFORE the trash-slot zeroing below, so arbitrary bits in the
            # trash block's scale rows (NaN included) are wiped by the same
            # jnp.where that wipes the page payload.
            k = k * ks_ref[0].astype(jnp.float32)[..., None]
            v = v * vs_ref[0].astype(jnp.float32)[..., None]
        # keys at positions >= ctx_len live in the trash block / a stale
        # table tail — their bits are arbitrary (NaN included).  Zero them
        # BEFORE the MXU: -inf score masking alone still lets NaN·0 leak
        # through the p@v product.
        kpos = w * bs + jax.lax.broadcasted_iota(
            jnp.int32, (1, bs, 1), dimension=1
        )                                                # [1, bs, 1]
        kvalid = kpos < ctx_len
        k = jnp.where(kvalid, k, 0.0)
        v = jnp.where(kvalid, v, 0.0)

        # batched over KV heads: [KV, TQ*G, hd] x [KV, bs, hd] -> s
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale                                        # [KV, TQ*G, bs]

        # per-query causal mask: flat row j is query t_eff*TQ + j//G at
        # absolute position ctx_len - q_len + that
        qi = t_eff * q_tile + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, dimension=1
        ) // G
        spos = w * bs + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, dimension=2
        )
        valid = (qi < q_len) & (spos <= ctx_len - q_len + qi)
        s = jnp.where(valid, s, -jnp.inf)

        m_prev = m_ref[...]                              # [KV, TQ*G, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # m_new can only be -inf while no valid key has been seen; the
        # guard keeps exp() finite for fully-masked query rows.
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        alpha = jnp.exp(jnp.where(jnp.isfinite(m_prev), m_prev - m_safe,
                                  -jnp.inf))             # [KV, TQ*G, 1]
        p = jnp.exp(s - m_safe)                          # [KV, TQ*G, bs]
        m_ref[...] = m_new
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                                # [KV, TQ*G, hd]

    @pl.when((w == num_w - 1) & in_row & (t == t_eff))
    def _finalize():
        KV, TQ, G, hd = o_ref.shape
        l = l_ref[...]
        # Fully-masked query rows (q_len == 0 seats, tile tails) keep
        # l == 0 → emit exact zeros, never NaN.
        out = acc_ref[...] / jnp.where(l == 0.0, 1.0, l)
        o_ref[...] = out.reshape(KV, TQ, G, hd).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("block_size", "q_tile", "kv_tile", "max_q_len",
                     "interpret"),
)
def paged_attention_ragged(
    q: jax.Array,             # [Tq, H, hd] flat packed queries
    k_cache: jax.Array,       # [num_blocks, KV, bs, hd] paged cache
    v_cache: jax.Array,       # [num_blocks, KV, bs, hd]
    block_tables: jax.Array,  # [R, W] int32 (0 = trash block)
    q_start: jax.Array,       # [R+1] int32, q_start[R] == Tq
    q_len: jax.Array,         # [R] int32 (0 = dead/padding row)
    ctx_len: jax.Array,       # [R] int32 context incl. the row's own tokens
    *,
    block_size: int,
    max_q_len: int,
    q_tile: int = 0,
    kv_tile: int = 0,
    interpret: bool = False,
    k_scale: jax.Array | None = None,  # [num_blocks, KV, bs] f32
    v_scale: jax.Array | None = None,  # [num_blocks, KV, bs] f32
) -> jax.Array:
    """Ragged paged attention over heterogeneous-length query rows.

    Row ``r`` owns flat query slots ``[q_start[r], q_start[r+1])`` (both
    multiples of ``q_tile``, at least one tile per row); the first
    ``q_len[r]`` slots are its queries at absolute positions
    ``ctx_len[r] - q_len[r] .. ctx_len[r] - 1``, whose K/V must already be
    scattered into the cache (how ``engine.model.forward`` orders things).
    ``max_q_len`` (static) bounds ``q_start[r+1] - q_start[r]``.  Returns
    ``[Tq, H, hd]``; slots past ``q_len[r]`` but inside an allotted tile
    that holds at least one valid query — and every slot of a dead row —
    come back as exact zeros.

    ``(q_tile, kv_tile)`` are pure performance knobs (``engine.autotune``
    sweeps them per shape class): ``q_tile`` sets the output tile height,
    ``kv_tile`` the per-grid-step key window.  ``kv_tile`` must divide
    ``block_size``; values below it sub-split each physical block into
    ``block_size // kv_tile`` grid steps that DMA consecutive slices of the
    same block (paged tables are non-contiguous, so a step can never span
    *more* than one block — tuning upward means growing ``block_size``
    itself, a cache-layout change the autotuner only ever recommends).
    ``0`` means the default (``min(max_q_len, 128)`` / ``block_size``).

    Quantized KV (``EngineConfig.kv_dtype`` int8/fp8): pass the per-(slot,
    head) float32 scale caches as ``k_scale``/``v_scale`` — the kernel
    dequantizes each K/V tile inside the launch (one multiply before the
    MXU), with the scales riding two extra block inputs whose index map is
    the 3-tuple analogue of the page ``kv_map`` (same trash-block routing,
    so skipped steps DMA block 0's scales and the in-kernel zeroing wipes
    them along with the payload).  ``None`` (the default) traces the exact
    unquantized kernel — byte-identical to the pre-quant path.
    """
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be passed together")
    quantized = k_scale is not None
    Tq, H, hd = q.shape
    KV = k_cache.shape[1]
    G = H // KV
    R, W = block_tables.shape
    bs = block_size
    if q_tile <= 0:
        q_tile = min(max_q_len, 128) if max_q_len % min(max_q_len, 128) == 0 \
            else max_q_len
    if max_q_len % q_tile or Tq % q_tile:
        raise ValueError(
            f"q_tile {q_tile} must divide max_q_len {max_q_len} and Tq {Tq}"
        )
    if kv_tile <= 0:
        kv_tile = bs
    if bs % kv_tile:
        raise ValueError(
            f"kv_tile {kv_tile} must divide block_size {bs}"
        )
    splits = bs // kv_tile
    num_t = max_q_len // q_tile

    # head-packed flat layout: [KV, Tq, G, hd] so a q tile is one
    # contiguous (KV, TQ, G, hd) block
    q4 = q.reshape(Tq, KV, G, hd).transpose(1, 0, 2, 3)

    def q_map(r, t, w, q_start, q_len, ctx_len, tables):
        _, t_eff = _row_tile(t, q_start, r, q_tile)
        return (0, q_start[r] // q_tile + t_eff, 0, 0)

    def kv_map(r, t, w, q_start, q_len, ctx_len, tables):
        # steps that do no work (dead tile, block past the tile's causal
        # frontier) DMA the always-resident trash block instead of real KV.
        # w walks sub-tiles: physical block w // splits, slice w % splits.
        alloc, t_eff = _row_tile(t, q_start, r, q_tile)
        live = (t < alloc) & (t_eff * q_tile < q_len[r])
        last_q = jnp.minimum((t_eff + 1) * q_tile, q_len[r]) - 1
        use = live & (w * kv_tile <= ctx_len[r] - q_len[r] + last_q)
        return (jnp.where(use, tables[r, w // splits], 0), 0, w % splits, 0)

    def scale_map(r, t, w, q_start, q_len, ctx_len, tables):
        # 3-tuple twin of kv_map for the [num_blocks, KV, bs] scale caches
        block, _, sub, _ = kv_map(r, t, w, q_start, q_len, ctx_len, tables)
        return (block, 0, sub)

    in_specs = [
        pl.BlockSpec((KV, q_tile, G, hd), q_map),
        pl.BlockSpec((1, KV, kv_tile, hd), kv_map),
        pl.BlockSpec((1, KV, kv_tile, hd), kv_map),
    ]
    operands = [q4, k_cache, v_cache]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, KV, kv_tile), scale_map),
            pl.BlockSpec((1, KV, kv_tile), scale_map),
        ]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(R, num_t, W * splits),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((KV, q_tile, G, hd), q_map),
        scratch_shapes=[
            pltpu.VMEM((KV, q_tile * G, 1), jnp.float32),
            pltpu.VMEM((KV, q_tile * G, 1), jnp.float32),
            pltpu.VMEM((KV, q_tile * G, hd), jnp.float32),
        ],
    )

    kernel = functools.partial(
        _ragged_kernel, kv_tile=kv_tile, q_tile=q_tile,
        scale=1.0 / (hd ** 0.5), quantized=quantized,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((KV, Tq, G, hd), q.dtype),
        interpret=interpret,
    )(q_start, q_len, ctx_len, block_tables, *operands)
    return out.transpose(1, 0, 2, 3).reshape(Tq, H, hd)


@functools.partial(
    jax.jit, static_argnames=("block_size", "kv_tile", "interpret")
)
def paged_attention_decode(
    q: jax.Array,          # [B, H, hd]
    k_cache: jax.Array,    # [num_blocks, KV, bs, hd] block-major paged cache
    v_cache: jax.Array,    # [num_blocks, KV, bs, hd]
    block_tables: jax.Array,  # [B, W] int32 (0 = trash block)
    seq_lens: jax.Array,      # [B] int32 (0 = padding row)
    *,
    block_size: int,
    kv_tile: int = 0,
    interpret: bool = False,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
) -> jax.Array:
    """Single-token-per-sequence paged attention.  Returns ``[B, H, hd]``.

    The decode face of the ragged kernel: every row is one query slot
    (``q_tile == 1``).  ``seq_lens[b]`` counts the valid context slots for
    row ``b`` *including* the token being decoded; ``seq_lens[b] == 0``
    rows emit exact zeros.  ``k_scale``/``v_scale`` carry quantized-KV
    dequant scales exactly as in :func:`paged_attention_ragged`.
    """
    B = q.shape[0]
    q_start = jnp.arange(B + 1, dtype=jnp.int32)
    q_len = (seq_lens > 0).astype(jnp.int32)
    return paged_attention_ragged(
        q, k_cache, v_cache, block_tables, q_start, q_len, seq_lens,
        block_size=block_size, max_q_len=1, q_tile=1, kv_tile=kv_tile,
        interpret=interpret, k_scale=k_scale, v_scale=v_scale,
    )
