"""Pallas TPU paged-attention decode kernel.

The decode step is the serving hot loop: every running sequence attends over
its full paged context once per generated token.  The einsum path in
``engine.model.forward`` first *materialises* the gathered context
(``[B, W*bs, KV, hd]`` in HBM) and then runs attention over it — two passes
over the context bytes.  This kernel streams each sequence's KV blocks
HBM→VMEM exactly once, driven by the block table, with flash-attention-style
online softmax so nothing is materialised.

Mechanics (the TPU-idiomatic part): the grid is ``(B, W)`` and the block
tables + context lengths ride ``PrefetchScalarGridSpec`` scalar prefetch, so
the K/V ``BlockSpec`` index maps *read the block table* to pick which
physical block Mosaic DMAs next — the pipeline does the paged gather for
free, double-buffered, overlapping the previous block's FLOPs.  All KV heads
of a page travel in one ``[KV, bs, hd]`` block (one contiguous DMA, few
large grid steps — a per-(b, kv, w) grid was measured 8× slower from
per-step overheads).

Role-equivalent to the paged-attention CUDA kernels inside the reference's
engines (vLLM); the reference itself ships only block-copy kernels
(ref: lib/llm/src/kernels/block_copy.cu:41).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _decode_kernel(
    # scalar prefetch
    tables_ref,    # [B, W] int32 physical block ids
    seq_lens_ref,  # [B] int32 context length (incl. current token)
    # blocks
    q_ref,         # [1, KV, G, hd]
    k_ref,         # [1, KV, bs, hd]
    v_ref,         # [1, KV, bs, hd]
    o_ref,         # [1, KV, G, hd]
    # scratch
    m_ref,         # [KV, G, 1] f32 running max
    l_ref,         # [KV, G, 1] f32 running denominator
    acc_ref,       # [KV, G, hd] f32 running numerator
    *,
    block_size: int,
    scale: float,
):
    b = pl.program_id(0)
    w = pl.program_id(1)
    num_w = pl.num_programs(1)
    seq_len = seq_lens_ref[b]

    @pl.when(w == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # Only blocks that hold context tokens contribute.
    @pl.when(w * block_size < seq_len)
    def _compute():
        q = q_ref[0].astype(jnp.float32)                 # [KV, G, hd]
        k = k_ref[0].astype(jnp.float32)                 # [KV, bs, hd]
        v = v_ref[0].astype(jnp.float32)                 # [KV, bs, hd]

        # batched over KV heads: [KV, G, hd] x [KV, bs, hd] -> [KV, G, bs]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale

        kpos = w * block_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, dimension=2
        )
        s = jnp.where(kpos < seq_len, s, -jnp.inf)

        m_prev = m_ref[...]                              # [KV, G, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # m_new can only be -inf while no valid key has been seen; the
        # guard keeps exp() finite for fully-masked blocks.
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        alpha = jnp.exp(jnp.where(jnp.isfinite(m_prev), m_prev - m_safe,
                                  -jnp.inf))             # [KV, G, 1]
        p = jnp.exp(s - m_safe)                          # [KV, G, bs]
        m_ref[...] = m_new
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                                # [KV, G, hd]

    @pl.when(w == num_w - 1)
    def _finalize():
        l = l_ref[...]
        # Zero-length (padding) rows produce l == 0 → emit zeros, not NaN.
        out = acc_ref[...] / jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block_size", "interpret")
)
def paged_attention_decode(
    q: jax.Array,          # [B, H, hd]
    k_cache: jax.Array,    # [num_blocks, KV, bs, hd] block-major paged cache
    v_cache: jax.Array,    # [num_blocks, KV, bs, hd]
    block_tables: jax.Array,  # [B, W] int32 (0 = trash block)
    seq_lens: jax.Array,      # [B] int32 (0 = padding row)
    *,
    block_size: int,
    interpret: bool = False,
) -> jax.Array:
    """Single-token-per-sequence paged attention.  Returns ``[B, H, hd]``.

    ``seq_lens[b]`` counts the valid context slots for row ``b`` *including*
    the token being decoded (whose K/V must already be scattered into the
    cache, which is how ``engine.model.forward`` orders things).
    """
    B, H, hd = q.shape
    KV = k_cache.shape[1]
    G = H // KV
    W = block_tables.shape[1]
    bs = block_size

    q4 = q.reshape(B, KV, G, hd)

    grid = (B, W)

    def q_map(b, w, tables, lens):
        return (b, 0, 0, 0)

    def kv_map(b, w, tables, lens):
        return (tables[b, w], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, KV, G, hd), q_map),
            pl.BlockSpec((1, KV, bs, hd), kv_map),
            pl.BlockSpec((1, KV, bs, hd), kv_map),
        ],
        out_specs=pl.BlockSpec((1, KV, G, hd), q_map),
        scratch_shapes=[
            pltpu.VMEM((KV, G, 1), jnp.float32),
            pltpu.VMEM((KV, G, 1), jnp.float32),
            pltpu.VMEM((KV, G, hd), jnp.float32),
        ],
    )

    kernel = functools.partial(
        _decode_kernel, block_size=bs, scale=1.0 / (hd ** 0.5)
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KV, G, hd), q.dtype),
        interpret=interpret,
    )(block_tables, seq_lens, q4, k_cache, v_cache)
    return out.reshape(B, H, hd)
