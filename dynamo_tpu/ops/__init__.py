"""Pallas TPU kernels for the hot ops.

Role-equivalent to the reference's CUDA kernels (ref: lib/llm/src/kernels/
block_copy.cu) plus the attention kernels the reference inherits from its
engines (vLLM paged attention).  Everything here is written against the
paged-KV layout owned by :mod:`dynamo_tpu.engine.model`.
"""

from .paged_attention import paged_attention_decode

__all__ = ["paged_attention_decode"]
