"""Speculative-decoding accounting (ref: protocols.rs:48 ``SpecDecodeStats``
inside ``ForwardPassMetrics``).

``drafted`` counts draft tokens fed to a verify window, ``accepted`` the
ones the target model confirmed, ``emitted`` every token a spec window
landed (accepted drafts + the bonus/corrective token), ``windows`` the
number of verify windows run. Serialisation defaults absent fields to
zero so mixed-version clusters (workers without spec) aggregate cleanly.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class SpecDecodeStats:
    drafted: int = 0
    accepted: int = 0
    emitted: int = 0
    windows: int = 0

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.drafted if self.drafted else 0.0

    def to_dict(self) -> dict:
        return {
            "drafted": int(self.drafted),
            "accepted": int(self.accepted),
            "emitted": int(self.emitted),
            "windows": int(self.windows),
            "acceptance_rate": float(self.acceptance_rate),
        }

    @classmethod
    def from_dict(cls, d) -> "SpecDecodeStats":
        d = d or {}
        return cls(
            drafted=int(d.get("drafted", 0)),
            accepted=int(d.get("accepted", 0)),
            emitted=int(d.get("emitted", 0)),
            windows=int(d.get("windows", 0)),
        )
