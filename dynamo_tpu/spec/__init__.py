"""Speculative decoding: device-side n-gram drafting + batched verify.

The subsystem has three pieces:

- :mod:`.ngram` — the prompt-lookup drafter. A jitted n-gram match of the
  last suffix of each sequence's on-device token history against that same
  history; no draft model, no extra host uploads during steady-state decode.
- ``raw_spec_window_fn`` in :mod:`..engine.model` — the batched verify
  window: ONE target-model forward over ``[B, k+1]`` ragged query tokens
  against the paged KV cache, accepting the longest matching prefix.
- :mod:`.stats` — ``SpecDecodeStats`` accounting (drafted / accepted /
  acceptance rate) published worker → metrics aggregator → planner.

Greedy rows have exact parity with ``spec_mode="off"`` (a hard invariant —
see ``tests/test_spec_decode.py``); sampled rows emit one token per window
with the same position-keyed RNG as the non-spec path when seeded.
"""

from .ngram import propose_drafts, propose_drafts_reference
from .stats import SpecDecodeStats

__all__ = ["propose_drafts", "propose_drafts_reference", "SpecDecodeStats"]
