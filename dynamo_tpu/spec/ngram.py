"""Prompt-lookup drafting: n-gram suffix match over on-device token history.

Each decode seat keeps a per-sequence token history row ``hist[0..pos0]``
(``hist[p]`` = token at position ``p``; -1 marks unknown positions, e.g.
prefix-cache gaps or unused tail). Drafting finds the most recent earlier
occurrence of the current suffix n-gram — trying the largest n first — and
proposes the k tokens that followed it. Proposals are *always* verified by
the target model, so a bad match costs throughput, never correctness.

``propose_drafts`` is the traced/jittable version used inside the spec
window fn; ``propose_drafts_reference`` is a plain-numpy oracle for tests.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..utils.hotpath import hot_path


@hot_path
def _propose_row(hist: jax.Array, pos0: jax.Array, k: int,
                 ngram_min: int, ngram_max: int) -> jax.Array:
    """Drafts for one history row.

    hist: [H] int32 tokens (-1 = unknown), pos0: scalar index of the last
    known token. Returns [k] int32 drafts, -1-padded; valid drafts form a
    contiguous prefix.
    """
    H = hist.shape[0]
    idx = jnp.arange(H, dtype=jnp.int32)
    found = jnp.zeros((), dtype=bool)
    best_q = jnp.full((), -1, dtype=jnp.int32)
    # largest n first: a longer context match is the better prediction
    for n in range(ngram_max, ngram_min - 1, -1):
        offs = jnp.arange(n, dtype=jnp.int32)
        sidx = pos0 - n + 1 + offs
        suf = hist[jnp.clip(sidx, 0, H - 1)]
        suffix_ok = (pos0 - n + 1 >= 0) & jnp.all(suf >= 0)
        # every candidate end position q gets its n-token window [q-n+1, q]
        widx = idx[:, None] - n + 1 + offs[None, :]            # [H, n]
        win = hist[jnp.clip(widx, 0, H - 1)]
        match = (
            jnp.all(win == suf[None, :], axis=1)
            & jnp.all(win >= 0, axis=1)
            & (idx >= n - 1) & (idx < pos0) & suffix_ok
        )
        # prefer the most recent match with a FULL k-token continuation
        # inside known history (on periodic content the nearest match sits
        # right at the suffix and only has 1-2 known followers); fall back
        # to the nearest match otherwise
        q_full = jnp.max(jnp.where(match & (idx + k <= pos0), idx, -1))
        q_any = jnp.max(jnp.where(match, idx, -1))
        q = jnp.where(q_full >= 0, q_full, q_any)
        use = (q >= 0) & ~found
        best_q = jnp.where(use, q, best_q)
        found = found | use
    didx = best_q + 1 + jnp.arange(k, dtype=jnp.int32)
    d = hist[jnp.clip(didx, 0, H - 1)]
    # a draft chain stops at the first unknown/overrun position
    ok = jnp.cumprod(
        (found & (didx <= pos0) & (d >= 0)).astype(jnp.int32)
    ).astype(bool)
    return jnp.where(ok, d, -1).astype(jnp.int32)


@hot_path
def propose_drafts(hist: jax.Array, pos0: jax.Array, k: int,
                   ngram_min: int, ngram_max: int) -> jax.Array:
    """Batched drafter: hist [B, H], pos0 [B] -> drafts [B, k] (-1-padded)."""
    return jax.vmap(
        lambda h, p: _propose_row(h, p, k, ngram_min, ngram_max)
    )(hist, pos0)


def propose_drafts_reference(hist, pos0: int, k: int,
                             ngram_min: int, ngram_max: int) -> np.ndarray:
    """Plain-python oracle for one row (tests compare the traced fn to this)."""
    hist = np.asarray(hist)
    out = np.full(k, -1, dtype=np.int32)
    for n in range(ngram_max, ngram_min - 1, -1):
        if pos0 - n + 1 < 0:
            continue
        suf = hist[pos0 - n + 1:pos0 + 1]
        if (suf < 0).any():
            continue
        best = best_full = -1
        for q in range(n - 1, min(pos0, hist.shape[0])):
            win = hist[q - n + 1:q + 1]
            if (win >= 0).all() and (win == suf).all():
                best = q
                if q + k <= pos0:
                    best_full = q
        if best_full >= 0:
            best = best_full
        if best < 0:
            continue
        for j in range(k):
            p = best + 1 + j
            if p > pos0 or hist[p] < 0:
                break
            out[j] = hist[p]
        return out
    return out
