"""Backend operator: engine token stream → post-processed text stream.

Role-equivalent to the reference's ``Backend`` (ref: lib/llm/src/
backend.rs:55): the forward edge folds tokenizer-derived stop configuration
into the wire request; the backward edge runs incremental detokenization
(UTF-8-safe), evaluates stop strings beyond what the engine can see (with
holdback so a stop string spanning two deltas is still caught before being
emitted), and accounts tokens into :class:`BackendOutput`.
"""

from __future__ import annotations

from typing import Any, AsyncIterator

from ..runtime.context import Context
from ..runtime.engine import Operator
from .protocols import BackendOutput, PreprocessedRequest
from .tokenizer import Tokenizer


class Backend(Operator):
    def __init__(self, tokenizer: Tokenizer):
        self.tokenizer = tokenizer

    async def forward(self, request: Any, context: Context) -> Any:
        req: PreprocessedRequest = request
        # engine-side stop set: model EOS + user stop_token_ids
        eos = set(req.stop.eos_token_ids) | set(req.stop.stop_token_ids)
        if req.mm is not None:
            return {**self._base_wire(req, eos), "mm": req.mm}
        return self._base_wire(req, eos)

    def _base_wire(self, req: PreprocessedRequest, eos) -> dict:
        return {
            "token_ids": req.token_ids,
            "model": req.model,
            "max_tokens": req.stop.max_tokens,
            "temperature": req.sampling.temperature,
            "top_k": req.sampling.top_k,
            "top_p": req.sampling.top_p,
            "seed": req.sampling.seed,
            "eos_token_ids": sorted(eos),
            "ignore_eos": req.stop.ignore_eos,
            "annotations": req.annotations,
            "router_hints": req.router_hints,
            # original stop strings travel too so a migrated request
            # re-creates identical semantics on the new worker
            "stop": req.stop.stop,
        }

    async def backward(  # type: ignore[override]
        self, stream: AsyncIterator[Any], request: Any, context: Context
    ) -> AsyncIterator[BackendOutput]:
        req: PreprocessedRequest = request
        detok = self.tokenizer.stream(req.token_ids)
        stops = [s for s in req.stop.stop if s]
        holdback = max((len(s) - 1 for s in stops), default=0)
        pending = ""   # detokenized but not yet emitted (stop-string window)
        cum = 0
        num_prompt = len(req.token_ids)

        def make(text: str, token_ids, reason=None) -> BackendOutput:
            return BackendOutput(
                token_ids=list(token_ids), text=text, finish_reason=reason,
                cum_tokens=cum, num_prompt_tokens=num_prompt,
            )

        async for item in stream:
            token_ids = list(item.get("token_ids", []))
            cum += len(token_ids)
            num_prompt = item.get("num_prompt_tokens", num_prompt)
            finished = bool(item.get("finished"))
            reason = item.get("finish_reason")
            pending += detok.push(token_ids)
            if finished:
                pending += detok.flush()
            if stops:
                hit = _find_stop(pending, stops)
                if hit is not None:
                    # truncate at the stop string; cancel the worker stream
                    context.stop_generating()
                    yield make(pending[:hit], token_ids, "stop")
                    return
            if finished:
                yield make(pending, token_ids, reason)
                return
            emit_len = len(pending) - holdback
            if emit_len > 0:
                yield make(pending[:emit_len], token_ids)
                pending = pending[emit_len:]
            else:
                yield make("", token_ids)
        # stream ended without a finished marker (worker died / cancelled)
        if pending:
            yield make(pending, [], "cancelled" if context.is_stopped() else None)


def _find_stop(text: str, stops) -> int | None:
    best = None
    for s in stops:
        i = text.find(s)
        if i >= 0 and (best is None or i < best):
            best = i
    return best
