"""Stream perf recorder + analysis
(ref: lib/llm/src/perf.rs:560, recorder.rs:667 — record response streams
with timestamps at minimal overhead, analyse offline).

``record_stream`` wraps any async output stream, appending
``(t_monotonic, kind, payload)`` tuples to an in-memory list (one append
per item — no I/O on the hot path). ``StreamRecord`` derives TTFT/ITL/
throughput; ``Recorder`` collects many streams and dumps JSONL for offline
tooling.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Dict, List, Optional


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              int(round(q / 100.0 * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


@dataclass
class StreamRecord:
    """One recorded stream: request-relative event timeline."""

    request_id: str
    t_start: float = field(default_factory=time.monotonic)
    # wall-clock anchor for t_start: lets offline tooling lay records from
    # different processes on one timeline (and join them against trace
    # spans, which carry the same anchor)
    t_start_unix: float = field(default_factory=time.time)
    trace_id: Optional[str] = None
    events: List[tuple] = field(default_factory=list)  # (dt, kind, payload)
    finished: bool = False

    def mark(self, kind: str, payload: Any = None) -> None:
        self.events.append((time.monotonic() - self.t_start, kind, payload))

    # ----------------------- derived metrics ---------------------------

    @property
    def item_times(self) -> List[float]:
        return [dt for dt, kind, _ in self.events if kind == "item"]

    @property
    def ttft_s(self) -> Optional[float]:
        t = self.item_times
        return t[0] if t else None

    @property
    def itl_s(self) -> List[float]:
        t = self.item_times
        return [b - a for a, b in zip(t, t[1:])]

    @property
    def duration_s(self) -> Optional[float]:
        return self.events[-1][0] if self.events else None

    @property
    def num_items(self) -> int:
        return len(self.item_times)

    def summary(self) -> dict:
        itl = sorted(self.itl_s)
        dur = self.duration_s or 0.0
        return {
            "request_id": self.request_id,
            "ttft_s": self.ttft_s,
            "itl_p50_s": _pct(itl, 50),
            "itl_p99_s": _pct(itl, 99),
            "num_items": self.num_items,
            "duration_s": dur,
            "items_per_s": self.num_items / dur if dur else 0.0,
            "finished": self.finished,
        }

    def to_jsonl(self) -> str:
        return json.dumps({
            "request_id": self.request_id,
            "t_start_unix": self.t_start_unix,
            **({"trace_id": self.trace_id} if self.trace_id else {}),
            "events": [
                {"dt": dt, "kind": kind,
                 **({"payload": payload} if payload is not None else {})}
                for dt, kind, payload in self.events
            ],
            "summary": self.summary(),
        })


class Recorder:
    """Collects stream records; optional JSONL sink."""

    def __init__(self, path: Optional[str] = None,
                 capture_payloads: bool = False):
        self.path = path
        self.capture_payloads = capture_payloads
        self.records: Dict[str, StreamRecord] = {}

    def start(self, request_id: str,
              trace_id: Optional[str] = None) -> StreamRecord:
        rec = StreamRecord(request_id=request_id, trace_id=trace_id)
        self.records[request_id] = rec
        return rec

    async def record_stream(
        self, request_id: str, stream: AsyncIterator,
        trace_id: Optional[str] = None,
    ) -> AsyncIterator:
        """Pass-through wrapper: timestamps every yielded item."""
        rec = self.start(request_id, trace_id=trace_id)
        try:
            async for item in stream:
                rec.mark("item", item if self.capture_payloads else None)
                yield item
            rec.finished = True
        except BaseException as e:
            rec.mark("error", repr(e))
            raise
        finally:
            rec.mark("end")
            if self.path:
                self.flush(request_id)

    def flush(self, request_id: str) -> None:
        rec = self.records.get(request_id)
        if rec is None or not self.path:
            return
        with open(self.path, "a") as f:
            f.write(rec.to_jsonl() + "\n")

    def aggregate(self) -> dict:
        """Fleet-level percentiles across all finished records."""
        ttfts = sorted(r.ttft_s for r in self.records.values()
                       if r.ttft_s is not None)
        itls = sorted(x for r in self.records.values() for x in r.itl_s)
        total_items = sum(r.num_items for r in self.records.values())
        return {
            "num_streams": len(self.records),
            "ttft_p50_s": _pct(ttfts, 50),
            "ttft_p99_s": _pct(ttfts, 99),
            "itl_p50_s": _pct(itls, 50),
            "itl_p99_s": _pct(itls, 99),
            "total_items": total_items,
        }


def load_jsonl(path: str) -> List[dict]:
    """Offline analysis loader."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
