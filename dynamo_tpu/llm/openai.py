"""OpenAI wire protocol: response builders, SSE codec, delta aggregation.

Role-equivalent to the reference's ``protocols/openai/*`` (chat/completions
wire types, SSE codec at codec.rs, delta aggregators at aggregator.rs:691).
Requests are accepted as plain dicts (validated), responses are built as
dicts — msgpack/JSON-friendly and engine-agnostic.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import AsyncIterator, Dict, List, Optional

from .protocols import BackendOutput

SSE_DONE = "data: [DONE]\n\n"


class RequestError(ValueError):
    """Client error → HTTP 400."""


def validate_chat_request(req: dict) -> None:
    if not isinstance(req.get("model"), str) or not req["model"]:
        raise RequestError("'model' is required")
    msgs = req.get("messages")
    if not isinstance(msgs, list) or not msgs:
        raise RequestError("'messages' must be a non-empty list")
    for m in msgs:
        if not isinstance(m, dict) or "role" not in m or "content" not in m:
            raise RequestError("each message needs 'role' and 'content'")
    _validate_sampling(req)


def validate_completion_request(req: dict) -> None:
    if not isinstance(req.get("model"), str) or not req["model"]:
        raise RequestError("'model' is required")
    if "prompt" not in req:
        raise RequestError("'prompt' is required")
    _validate_sampling(req)


def _validate_sampling(req: dict) -> None:
    t = req.get("temperature")
    if t is not None and not (0.0 <= float(t) <= 2.0):
        raise RequestError("temperature must be in [0, 2]")
    p = req.get("top_p")
    if p is not None and not (0.0 < float(p) <= 1.0):
        raise RequestError("top_p must be in (0, 1]")
    mt = req.get("max_tokens") or req.get("max_completion_tokens")
    if mt is not None and int(mt) < 1:
        raise RequestError("max_tokens must be >= 1")
    n = req.get("n")
    if n is not None and int(n) != 1:
        raise RequestError("only n=1 is supported")


# ---------------------------- id helpers ----------------------------------


def chat_id() -> str:
    return f"chatcmpl-{uuid.uuid4().hex}"


def completion_id() -> str:
    return f"cmpl-{uuid.uuid4().hex}"


# ------------------------- chunk construction ------------------------------


def chat_chunk(
    rid: str, model: str, created: int, *,
    content: Optional[str] = None,
    role: Optional[str] = None,
    finish_reason: Optional[str] = None,
    usage: Optional[dict] = None,
    reasoning: Optional[str] = None,
    tool_calls: Optional[list] = None,
) -> dict:
    delta: dict = {}
    if role is not None:
        delta["role"] = role
    if content is not None:
        delta["content"] = content
    if reasoning:
        delta["reasoning_content"] = reasoning
    if tool_calls:
        delta["tool_calls"] = tool_calls
    out = {
        "id": rid,
        "object": "chat.completion.chunk",
        "created": created,
        "model": model,
        "choices": [
            {"index": 0, "delta": delta, "finish_reason": finish_reason}
        ],
    }
    if usage is not None:
        out["usage"] = usage
    return out


def completion_chunk(
    rid: str, model: str, created: int, *,
    text: str = "",
    finish_reason: Optional[str] = None,
    usage: Optional[dict] = None,
) -> dict:
    out = {
        "id": rid,
        "object": "text_completion",
        "created": created,
        "model": model,
        "choices": [
            {"index": 0, "text": text, "finish_reason": finish_reason,
             "logprobs": None}
        ],
    }
    if usage is not None:
        out["usage"] = usage
    return out


def usage_dict(prompt_tokens: int, completion_tokens: int) -> dict:
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }


def _map_finish(reason: Optional[str]) -> Optional[str]:
    # engine reasons → OpenAI finish_reason values
    if reason in (None, "stop", "length"):
        return reason
    if reason == "cancelled":
        return "stop"
    return "stop" if reason else None


# --------------------------- stream folding --------------------------------


async def chat_stream(
    outputs: AsyncIterator[BackendOutput], rid: str, model: str,
    parser=None,
) -> AsyncIterator[dict]:
    """Fold BackendOutputs into chat.completion.chunk frames.

    ``parser`` (llm.parsers.StreamParserPipeline) re-splits decoded text
    into content / reasoning_content / tool_calls deltas."""
    created = int(time.time())
    yield chat_chunk(rid, model, created, role="assistant", content="")
    prompt_tokens = 0
    cum = 0
    reason = "stop"
    saw_tool_calls = False

    def _frames(text):
        nonlocal saw_tool_calls
        if parser is None:
            if text:
                yield chat_chunk(rid, model, created, content=text)
            return
        d = parser.push(text)
        if not d.empty:
            saw_tool_calls = saw_tool_calls or bool(d.tool_calls)
            yield chat_chunk(
                rid, model, created, content=d.content or None,
                reasoning=d.reasoning, tool_calls=d.tool_calls,
            )

    async for out in outputs:
        prompt_tokens = out.num_prompt_tokens or prompt_tokens
        cum = out.cum_tokens or cum
        if out.finish_reason is not None:
            reason = out.finish_reason
            for f in _frames(out.text or ""):
                yield f
            break
        for f in _frames(out.text or ""):
            yield f
    if parser is not None:
        d = parser.flush()
        if not d.empty:
            saw_tool_calls = saw_tool_calls or bool(d.tool_calls)
            yield chat_chunk(
                rid, model, created, content=d.content or None,
                reasoning=d.reasoning, tool_calls=d.tool_calls,
            )
    finish = _map_finish(reason) or "stop"
    if saw_tool_calls and finish == "stop":
        finish = "tool_calls"
    yield chat_chunk(
        rid, model, created, finish_reason=finish,
        usage=usage_dict(prompt_tokens, cum),
    )


async def completion_stream(
    outputs: AsyncIterator[BackendOutput], rid: str, model: str
) -> AsyncIterator[dict]:
    created = int(time.time())
    prompt_tokens = 0
    cum = 0
    reason = "stop"
    async for out in outputs:
        prompt_tokens = out.num_prompt_tokens or prompt_tokens
        cum = out.cum_tokens or cum
        if out.finish_reason is not None:
            reason = out.finish_reason
            if out.text:
                yield completion_chunk(rid, model, created, text=out.text)
            break
        if out.text:
            yield completion_chunk(rid, model, created, text=out.text)
    yield completion_chunk(
        rid, model, created, finish_reason=_map_finish(reason) or "stop",
        usage=usage_dict(prompt_tokens, cum),
    )


# ---------------------------- aggregation ----------------------------------


async def aggregate_chat(chunks: AsyncIterator[dict]) -> dict:
    """Collapse a chunk stream into one chat.completion response
    (ref: aggregator.rs:691 — used for stream=false)."""
    rid = model = ""
    created = 0
    text_parts: List[str] = []
    reasoning_parts: List[str] = []
    tool_calls: List[dict] = []
    role = "assistant"
    finish = "stop"
    usage = None
    async for c in chunks:
        rid, model, created = c["id"], c["model"], c["created"]
        choice = c["choices"][0]
        delta = choice.get("delta", {})
        if delta.get("role"):
            role = delta["role"]
        if delta.get("content"):
            text_parts.append(delta["content"])
        if delta.get("reasoning_content"):
            reasoning_parts.append(delta["reasoning_content"])
        if delta.get("tool_calls"):
            tool_calls.extend(delta["tool_calls"])
        if choice.get("finish_reason"):
            finish = choice["finish_reason"]
        if c.get("usage"):
            usage = c["usage"]
    message: dict = {"role": role, "content": "".join(text_parts)}
    if reasoning_parts:
        message["reasoning_content"] = "".join(reasoning_parts)
    if tool_calls:
        message["tool_calls"] = tool_calls
    return {
        "id": rid,
        "object": "chat.completion",
        "created": created,
        "model": model,
        "choices": [
            {"index": 0, "message": message, "finish_reason": finish}
        ],
        "usage": usage or usage_dict(0, 0),
    }


async def aggregate_completion(chunks: AsyncIterator[dict]) -> dict:
    rid = model = ""
    created = 0
    text_parts: List[str] = []
    finish = "stop"
    usage = None
    async for c in chunks:
        rid, model, created = c["id"], c["model"], c["created"]
        choice = c["choices"][0]
        if choice.get("text"):
            text_parts.append(choice["text"])
        if choice.get("finish_reason"):
            finish = choice["finish_reason"]
        if c.get("usage"):
            usage = c["usage"]
    return {
        "id": rid,
        "object": "text_completion",
        "created": created,
        "model": model,
        "choices": [
            {"index": 0, "text": "".join(text_parts),
             "finish_reason": finish, "logprobs": None}
        ],
        "usage": usage or usage_dict(0, 0),
    }


# --------------------------- responses API ---------------------------------
# (ref: the /v1/responses route in lib/llm/src/http/service/openai.rs:714 —
#  the OpenAI Responses surface mapped onto the chat pipeline)


def response_id() -> str:
    return f"resp-{uuid.uuid4().hex}"


def _responses_input_to_messages(inp, instructions=None) -> List[dict]:
    """Responses ``input`` (string | message list) → chat messages."""
    messages: List[dict] = []
    if instructions:
        messages.append({"role": "system", "content": str(instructions)})
    if isinstance(inp, str):
        messages.append({"role": "user", "content": inp})
        return messages
    if not isinstance(inp, list):
        raise RequestError("input must be a string or a list of messages")
    for item in inp:
        if not isinstance(item, dict):
            raise RequestError("input items must be message objects")
        role = item.get("role", "user")
        content = item.get("content", "")
        if isinstance(content, list):
            # content parts: keep the text parts
            content = "".join(
                p.get("text", "") for p in content
                if isinstance(p, dict)
                and p.get("type") in ("input_text", "output_text", "text")
            )
        messages.append({"role": role, "content": content})
    return messages


def responses_to_chat(req: dict) -> dict:
    """Translate a /v1/responses body into the chat-pipeline request."""
    if "input" not in req:
        raise RequestError("missing 'input'")
    body: dict = {
        "model": req.get("model", ""),
        "messages": _responses_input_to_messages(
            req["input"], req.get("instructions")
        ),
    }
    if req.get("max_output_tokens") is not None:
        body["max_tokens"] = req["max_output_tokens"]
    for key in ("temperature", "top_p", "seed", "stop"):
        if req.get(key) is not None:
            body[key] = req[key]
    _validate_sampling(body)
    return body


def response_object(
    rid: str, model: str, text: str, usage: Optional[dict],
    status: str = "completed",
) -> dict:
    usage = usage or usage_dict(0, 0)
    return {
        "id": rid,
        "object": "response",
        "created_at": int(time.time()),
        "status": status,
        "model": model,
        "output": [{
            "type": "message",
            "id": f"{rid}-msg0",
            "status": status,
            "role": "assistant",
            "content": [{"type": "output_text", "text": text,
                         "annotations": []}],
        }],
        "usage": {
            "input_tokens": usage.get("prompt_tokens", 0),
            "output_tokens": usage.get("completion_tokens", 0),
            "total_tokens": usage.get("total_tokens", 0),
        },
    }


def chat_to_response(agg: dict, rid: str, model: str) -> dict:
    """Aggregated chat.completion → Responses object."""
    choice = agg["choices"][0]
    finish = choice.get("finish_reason")
    return response_object(
        rid, model, choice["message"].get("content") or "",
        agg.get("usage"),
        status="completed" if finish in ("stop", "tool_calls", "length")
        else "incomplete",
    )


async def responses_stream(
    chunks: AsyncIterator[dict], rid: str, model: str
) -> AsyncIterator[tuple]:
    """chat.completion.chunk stream → (event_type, payload) Responses SSE
    events: response.created → response.output_text.delta* →
    response.completed."""
    yield "response.created", {
        "type": "response.created",
        "response": {"id": rid, "object": "response",
                     "status": "in_progress", "model": model},
    }
    parts: List[str] = []
    usage = None
    async for c in chunks:
        delta = c["choices"][0].get("delta", {})
        if c.get("usage"):
            usage = c["usage"]
        text = delta.get("content")
        if text:
            parts.append(text)
            yield "response.output_text.delta", {
                "type": "response.output_text.delta",
                "item_id": f"{rid}-msg0",
                "output_index": 0,
                "delta": text,
            }
    yield "response.completed", {
        "type": "response.completed",
        "response": response_object(rid, model, "".join(parts), usage),
    }


# ------------------------------- SSE ---------------------------------------


def sse_frame(payload: dict) -> str:
    return f"data: {json.dumps(payload, separators=(',', ':'))}\n\n"


def sse_event(event: str, payload: dict) -> str:
    return (f"event: {event}\n"
            f"data: {json.dumps(payload, separators=(',', ':'))}\n\n")


def models_response(models: List[dict]) -> dict:
    return {
        "object": "list",
        "data": [
            {"id": m["name"], "object": "model",
             "created": m.get("created", 0), "owned_by": "dynamo-tpu"}
            for m in models
        ],
    }
