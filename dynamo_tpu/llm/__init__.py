"""LLM pipeline layer: tokenization, preprocessing, backend post-processing,
wire protocols, discovery — the TPU-native equivalent of the reference's
``lib/llm`` (ref: lib/llm/src/lib.rs:13-44)."""
