"""Migration: retry a broken stream on a new worker with token carryover.

Role-equivalent to the reference's ``Migration``/``RetryManager``
(ref: lib/llm/src/migration.rs:26,88-190): when a worker dies mid-stream (or
no worker is available at issue time), the request is re-issued to another
instance with the tokens generated so far appended to the prompt, so
generation continues seamlessly. Bounded by ``migration_limit`` from the
model card AND by the request's remaining deadline budget: each retry waits
a jittered exponential backoff clipped to what is left of the deadline, and
an expired deadline surfaces as a non-retryable ``ERR_TIMEOUT`` instead of
burning further attempts on work the client will never see.
"""

from __future__ import annotations

import asyncio
import random
from typing import Any, AsyncIterator, Optional

from ..runtime.context import Context
from ..runtime.engine import AsyncEngine
from ..runtime.transport import (
    EngineError, ERR_DRAINING, ERR_OVERLOADED, ERR_TIMEOUT, ERR_UNAVAILABLE,
)
from ..tracing import get_tracer, trace_span
from ..utils.logging import get_logger

log = get_logger("migration")

# ``draining`` is a planned divert (the router routes the retry elsewhere),
# not a failure — retryable like unavailability but never breaker-tripping
RETRYABLE = (ERR_UNAVAILABLE, ERR_OVERLOADED, ERR_DRAINING)


class Migration(AsyncEngine):
    """Wraps the routing sink; retries with accumulated-token carryover."""

    def __init__(
        self,
        sink: AsyncEngine,
        migration_limit: int = 3,
        *,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 2.0,
        rng: Optional[random.Random] = None,
    ):
        self.sink = sink
        self.migration_limit = migration_limit
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        # injectable for deterministic jitter in tests
        self.rng = rng or random.Random()
        # re-issue decisions taken over this sink's lifetime: the direct
        # stream-repair evidence the replay fault-attribution check reads
        # (span surplus undercounts when an unrelated request's timeout
        # cancels its attempt span before export)
        self.num_retries = 0

    async def _backoff(self, attempt: int, context: Context) -> bool:
        """Sleep the jittered backoff for retry number ``attempt`` (1-based),
        clipped to the remaining deadline budget. Returns False when the
        budget is exhausted or the caller cancelled — do not re-issue."""
        delay = min(
            self.backoff_base_s * (2 ** (attempt - 1)), self.backoff_cap_s
        ) * (0.5 + 0.5 * self.rng.random())
        remaining = context.time_remaining()
        if remaining is not None:
            if remaining <= 0:
                return False
            # never sleep past the deadline; leave a sliver to actually run
            delay = min(delay, max(remaining - 0.001, 0.0))
        if delay > 0:
            # a cancel during backoff must exit immediately, not re-issue
            # after the nap
            try:
                await asyncio.wait_for(context.wait_stopped(), timeout=delay)
            except asyncio.TimeoutError:
                pass
        return not context.is_stopped() and not context.is_expired()

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[Any]:
        req = dict(request)
        orig_prompt_len = len(req.get("token_ids", []))
        emitted: list = []
        attempts_left = self.migration_limit
        attempt = 0
        while True:
            # the attempt's child context mints the span id the attempt span
            # adopts: router/transport spans issued under attempt_ctx parent
            # here, and each retry is a sibling under the request root
            attempt_ctx = context.child()
            span = get_tracer().start_span(
                "migration.attempt", trace=attempt_ctx.trace,
                parent_span_id=context.trace.span_id,
                attrs={"attempt": attempt, "carried_tokens": len(emitted)},
            )
            stream = self.sink.generate(req, attempt_ctx)
            try:
                async for item in stream:
                    toks = list(item.get("token_ids", []))
                    emitted.extend(toks)
                    # report the *original* prompt length even after
                    # carryover re-issue (ref: migration.rs track_response)
                    if item.get("num_prompt_tokens", 0) > orig_prompt_len:
                        item = dict(item)
                        item["num_prompt_tokens"] = orig_prompt_len
                    yield item
                    if item.get("finished"):
                        return
                # stream completed without a finished marker: treat as a
                # worker drop unless the caller cancelled
                if context.is_stopped():
                    return
                raise EngineError("stream ended early", ERR_UNAVAILABLE)
            except EngineError as e:
                # close the attempt span BEFORE the backoff sleep below —
                # the nap belongs to migration.backoff, not the attempt
                span.set_status("error", e.code)
                span.end()
                if context.is_stopped():
                    return  # client gone — nobody is listening for a retry
                if e.code not in RETRYABLE or attempts_left <= 0:
                    raise
                if context.is_expired():
                    raise EngineError(
                        f"deadline exhausted after {attempt} migrations "
                        f"({len(emitted)} tokens emitted): {e}", ERR_TIMEOUT,
                    )
                attempts_left -= 1
                attempt += 1
                self.num_retries += 1
                with trace_span("migration.backoff", context,
                                attrs={"attempt": attempt}):
                    backed_off = await self._backoff(attempt, context)
                if not backed_off:
                    if context.is_stopped():
                        return
                    raise EngineError(
                        f"deadline exhausted during migration backoff "
                        f"(attempt {attempt}): {e}", ERR_TIMEOUT,
                    )
                log.warning(
                    "stream failed (%s); migrating with %d carried tokens "
                    "(%d attempts left)", e.code, len(emitted), attempts_left,
                )
                req = dict(request)
                req["token_ids"] = (
                    list(request.get("token_ids", [])) + emitted
                )
                remaining = int(request.get("max_tokens", 64)) - len(emitted)
                if remaining <= 0:
                    return  # everything already generated
                req["max_tokens"] = remaining
            finally:
                # close the sink stream deterministically — returning from
                # the async-for (e.g. on the finished item) would otherwise
                # leave the sink's cleanup (breaker bookkeeping, load
                # accounting) to run at GC time
                await stream.aclose()
                span.end()  # no-op on the error path (already closed)
