"""Migration: retry a broken stream on a new worker with token carryover.

Role-equivalent to the reference's ``Migration``/``RetryManager``
(ref: lib/llm/src/migration.rs:26,88-190): when a worker dies mid-stream (or
no worker is available at issue time), the request is re-issued to another
instance with the tokens generated so far appended to the prompt, so
generation continues seamlessly. Bounded by ``migration_limit`` from the
model card.
"""

from __future__ import annotations

from typing import Any, AsyncIterator

from ..runtime.context import Context
from ..runtime.engine import AsyncEngine
from ..runtime.transport import EngineError, ERR_OVERLOADED, ERR_UNAVAILABLE
from ..utils.logging import get_logger

log = get_logger("migration")

RETRYABLE = (ERR_UNAVAILABLE, ERR_OVERLOADED)


class Migration(AsyncEngine):
    """Wraps the routing sink; retries with accumulated-token carryover."""

    def __init__(self, sink: AsyncEngine, migration_limit: int = 3):
        self.sink = sink
        self.migration_limit = migration_limit

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[Any]:
        req = dict(request)
        orig_prompt_len = len(req.get("token_ids", []))
        emitted: list = []
        attempts_left = self.migration_limit
        while True:
            got_any_this_attempt = False
            try:
                async for item in self.sink.generate(req, context.child()):
                    toks = list(item.get("token_ids", []))
                    emitted.extend(toks)
                    got_any_this_attempt = True
                    # report the *original* prompt length even after
                    # carryover re-issue (ref: migration.rs track_response)
                    if item.get("num_prompt_tokens", 0) > orig_prompt_len:
                        item = dict(item)
                        item["num_prompt_tokens"] = orig_prompt_len
                    yield item
                    if item.get("finished"):
                        return
                # stream completed without a finished marker: treat as a
                # worker drop unless the caller cancelled
                if context.is_stopped():
                    return
                raise EngineError("stream ended early", ERR_UNAVAILABLE)
            except EngineError as e:
                if (e.code not in RETRYABLE or attempts_left <= 0
                        or context.is_stopped()):
                    raise
                attempts_left -= 1
                log.warning(
                    "stream failed (%s); migrating with %d carried tokens "
                    "(%d attempts left)", e.code, len(emitted), attempts_left,
                )
                req = dict(request)
                req["token_ids"] = (
                    list(request.get("token_ids", [])) + emitted
                )
                remaining = int(request.get("max_tokens", 64)) - len(emitted)
                if remaining <= 0:
                    return  # everything already generated
                req["max_tokens"] = remaining
                # re-issue loop continues; tiny guard against hot-looping on
                # instantly-failing instances is the attempt bound itself
                _ = got_any_this_attempt
