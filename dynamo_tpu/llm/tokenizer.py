"""Tokenizer wrapper + incremental streaming detokenizer.

Role-equivalent to the reference's tokenizer layer (ref: lib/llm/src/
tokenizers.rs:564 and the incremental ``DecodeStream``): wraps a HuggingFace
``tokenizers.Tokenizer`` (tokenizer.json) and provides a per-request
:class:`DetokenizerStream` that emits only complete UTF-8 text — a token
boundary mid-codepoint yields an empty delta until the character completes.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence

_REPLACEMENT = "�"


class Tokenizer:
    """Uniform facade over a HF ``tokenizers.Tokenizer``.

    Carries everything the pipeline needs: encode/decode, special-token ids,
    and the model's chat template (read from ``tokenizer_config.json`` when
    loading a pretrained directory).
    """

    def __init__(
        self,
        backing,
        *,
        eos_token_ids: Sequence[int] = (),
        bos_token_id: Optional[int] = None,
        chat_template: Optional[str] = None,
    ):
        self._tk = backing
        self.eos_token_ids: tuple = tuple(eos_token_ids)
        self.bos_token_id = bos_token_id
        self.chat_template = chat_template

    # -- construction --

    @staticmethod
    def from_file(path: str, **kw) -> "Tokenizer":
        from tokenizers import Tokenizer as HFTokenizer

        return Tokenizer(HFTokenizer.from_file(path), **kw)

    @staticmethod
    def from_json_str(data: str, **kw) -> "Tokenizer":
        """Rebuild from a serialized tokenizer.json string (how the model
        card ships the tokenizer through the store, the role the reference's
        NATS object store plays for MDCs; ref: model_card.rs:266)."""
        from tokenizers import Tokenizer as HFTokenizer

        return Tokenizer(HFTokenizer.from_str(data), **kw)

    def to_json_str(self) -> str:
        return self._tk.to_str()

    @staticmethod
    def from_pretrained_dir(path: str) -> "Tokenizer":
        """Load tokenizer.json + tokenizer_config.json from a local HF dir."""
        from tokenizers import Tokenizer as HFTokenizer

        tk = HFTokenizer.from_file(os.path.join(path, "tokenizer.json"))
        cfg_path = os.path.join(path, "tokenizer_config.json")
        eos_ids: List[int] = []
        bos_id = None
        chat_template = None
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                cfg = json.load(f)
            chat_template = cfg.get("chat_template")

            def _tok_id(entry):
                if entry is None:
                    return None
                content = (entry if isinstance(entry, str)
                           else entry.get("content"))
                return tk.token_to_id(content) if content else None

            eos = _tok_id(cfg.get("eos_token"))
            if eos is not None:
                eos_ids.append(eos)
            bos_id = _tok_id(cfg.get("bos_token"))
        return Tokenizer(
            tk, eos_token_ids=eos_ids, bos_token_id=bos_id,
            chat_template=chat_template,
        )

    # -- core api --

    def encode(self, text: str, add_special_tokens: bool = False) -> List[int]:
        return list(self._tk.encode(text, add_special_tokens=add_special_tokens).ids)

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._tk.decode(list(ids), skip_special_tokens=skip_special_tokens)

    @property
    def vocab_size(self) -> int:
        return self._tk.get_vocab_size()

    def stream(self, prompt_ids: Sequence[int] = ()) -> "DetokenizerStream":
        return DetokenizerStream(self, prompt_ids)


class DetokenizerStream:
    """Incremental detokenization with UTF-8 boundary handling.

    The sliding two-offset algorithm: decode from ``prefix_offset`` twice —
    once up to ``read_offset`` (already-emitted text) and once to the end —
    and emit the difference only when it is longer and does not end in a
    replacement character (i.e. the trailing codepoint is complete). Tokens
    that merely extend an incomplete codepoint emit ``""``.
    """

    def __init__(self, tokenizer: Tokenizer, prompt_ids: Sequence[int] = ()):
        self._tk = tokenizer
        # seed with the prompt tail so the first generated token detokenizes
        # with correct merge context (e.g. leading-space handling)
        self._ids: List[int] = list(prompt_ids)[-8:]
        self._prefix_offset = 0
        self._read_offset = len(self._ids)
        self.text = ""  # generated text emitted so far

    def push(self, token_ids: Sequence[int]) -> str:
        """Add newly generated token(s); return the completed text delta."""
        self._ids.extend(token_ids)
        prefix = self._tk.decode(self._ids[self._prefix_offset:self._read_offset])
        full = self._tk.decode(self._ids[self._prefix_offset:])
        if len(full) <= len(prefix) or full.endswith(_REPLACEMENT):
            return ""
        delta = full[len(prefix):]
        self._prefix_offset = self._read_offset
        self._read_offset = len(self._ids)
        self.text += delta
        return delta

    def flush(self) -> str:
        """Emit whatever remains (possibly with replacement chars) at EOS."""
        prefix = self._tk.decode(self._ids[self._prefix_offset:self._read_offset])
        full = self._tk.decode(self._ids[self._prefix_offset:])
        delta = full[len(prefix):] if len(full) > len(prefix) else ""
        self._prefix_offset = self._read_offset = len(self._ids)
        self.text += delta
        return delta
