"""Pipeline builder: wire frontend → preprocessor → backend → migration → router.

Role-equivalent to the reference's ``build_routed_pipeline``
(ref: lib/llm/src/entrypoint/input/common.rs:226,303-310). The returned
engine accepts OpenAI request dicts and yields :class:`BackendOutput`s; the
HTTP layer folds those into OpenAI SSE frames.
"""

from __future__ import annotations

from typing import Any, AsyncIterator, Optional

from ..runtime.component import Client
from ..runtime.context import Context
from ..runtime.engine import AsyncEngine, link
from .backend import Backend
from .discovery import ModelDeploymentCard
from .migration import Migration
from .preprocessor import Preprocessor


class PushSink(AsyncEngine):
    """Routing sink over a component Client (ref: push_router.rs:33).

    Modes: round_robin | random | direct:<instance_id>. KV-aware routing
    plugs in as its own sink (see router/).
    """

    def __init__(self, client: Client, mode: str = "round_robin"):
        self.client = client
        self.mode = mode

    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        if self.mode == "random":
            return self.client.random(request, context)
        if self.mode.startswith("direct:"):
            return self.client.direct(
                int(self.mode.split(":", 1)[1]), request, context
            )
        return self.client.round_robin(request, context)


def build_routed_pipeline(
    card: ModelDeploymentCard,
    client: Client,
    *,
    router_mode: str = "round_robin",
    sink: Optional[AsyncEngine] = None,
) -> AsyncEngine:
    """OpenAI dict in → BackendOutput stream out, over the cluster."""
    tokenizer = card.load_tokenizer()
    pre = Preprocessor(
        tokenizer,
        model_name=card.name,
        max_context_len=card.context_length,
    )
    back = Backend(tokenizer)
    inner = sink or PushSink(client, router_mode)
    return link(pre, back, Migration(inner, card.migration_limit))


def build_local_pipeline(
    engine: AsyncEngine,
    tokenizer,
    model_name: str = "local",
    max_context_len: int = 8192,
) -> AsyncEngine:
    """OpenAI dict in → BackendOutput stream out, over an IN-PROCESS engine
    (the dynamo-run quickstart shape: no store, no transport — ref:
    EngineConfig::StaticFull, entrypoint.rs:44)."""
    pre = Preprocessor(
        tokenizer, model_name=model_name, max_context_len=max_context_len
    )
    back = Backend(tokenizer)
    return link(pre, back, engine)


async def make_kv_sink(
    card: ModelDeploymentCard, client: Client, **router_kwargs
):
    """Build + start a KV-aware routing sink for ``build_routed_pipeline``
    (ref: KvPushRouter kv_router.rs:423). Returns ``(sink, router)`` so the
    caller can ``router.stop()`` at teardown."""
    from ..router.kv_router import KvPushRouter, KvRouter

    router = KvRouter(
        client, client.endpoint.component,
        block_size=card.kv_block_size, **router_kwargs,
    )
    await router.start()
    return KvPushRouter(router), router
