"""Pipeline builder: wire frontend → preprocessor → backend → migration → router.

Role-equivalent to the reference's ``build_routed_pipeline``
(ref: lib/llm/src/entrypoint/input/common.rs:226,303-310). The returned
engine accepts OpenAI request dicts and yields :class:`BackendOutput`s; the
HTTP layer folds those into OpenAI SSE frames.
"""

from __future__ import annotations

from typing import Any, AsyncIterator, Optional

from ..runtime.component import Client
from ..runtime.context import Context
from ..runtime.engine import AsyncEngine, link
from .backend import Backend
from .discovery import ModelDeploymentCard
from .migration import Migration
from .preprocessor import Preprocessor


class PushSink(AsyncEngine):
    """Routing sink over a component Client (ref: push_router.rs:33).

    Modes: round_robin | random | direct:<instance_id>. KV-aware routing
    plugs in as its own sink (see router/).
    """

    def __init__(self, client: Client, mode: str = "round_robin"):
        self.client = client
        self.mode = mode

    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        if self.mode == "random":
            return self.client.random(request, context)
        if self.mode.startswith("direct:"):
            return self.client.direct(
                int(self.mode.split(":", 1)[1]), request, context
            )
        return self.client.round_robin(request, context)


def build_routed_pipeline(
    card: ModelDeploymentCard,
    client: Client,
    *,
    router_mode: str = "round_robin",
    sink: Optional[AsyncEngine] = None,
    mm_processor=None,
    tokenizer=None,
) -> AsyncEngine:
    """OpenAI dict in → BackendOutput stream out, over the cluster.

    ``mm_processor`` (multimodal.MultimodalProcessor) upgrades the
    preprocessor to the encode-prefill-decode flow for requests carrying
    image content parts. Pass ``tokenizer`` when the caller already loaded
    it (loading twice per registration doubles model-add latency)."""
    tokenizer = tokenizer or card.load_tokenizer()
    pre = Preprocessor(
        tokenizer,
        model_name=card.name,
        max_context_len=card.context_length,
    )
    if mm_processor is not None:
        from ..multimodal.processor import MultimodalPreprocessor

        pre = MultimodalPreprocessor(pre, mm_processor)
    back = Backend(tokenizer)
    inner = sink or PushSink(client, router_mode)
    return link(pre, back, Migration(inner, card.migration_limit))


class EmbeddingsPipeline:
    """Tokenise → worker ``embed`` endpoint → vectors
    (ref: the embeddings path of openai.rs:714; tokenisation mirrors the
    generation preprocessor, pooling happens on-device in the engine)."""

    def __init__(self, card: ModelDeploymentCard, client: Client,
                 tokenizer=None):
        # accept a shared tokenizer — loading twice per model registration
        # (once here, once in build_routed_pipeline) doubles add latency
        self.tokenizer = tokenizer or card.load_tokenizer()
        self.client = client
        self.max_context_len = card.context_length

    async def embed(self, inputs) -> tuple:
        """inputs: str | [str] | [int] | [[int]] → (vectors, prompt_tokens).
        Raises ValueError (→ HTTP 400) on any other shape."""
        if isinstance(inputs, str):
            inputs = [inputs]
        elif isinstance(inputs, list):
            if inputs and all(type(i) is int for i in inputs):
                inputs = [inputs]
        else:
            raise ValueError(
                "input must be a string, a list of strings, or token arrays"
            )
        batch = []
        for item in inputs:
            if isinstance(item, str):
                ids = self.tokenizer.encode(item)
            elif (isinstance(item, list)
                  and all(type(i) is int for i in item)):
                ids = list(item)
            else:
                raise ValueError(
                    "each input must be a string or an array of token ids"
                )
            if not ids:
                raise ValueError("empty embedding input")
            if len(ids) >= self.max_context_len:
                raise ValueError(
                    f"input of {len(ids)} tokens exceeds the "
                    f"{self.max_context_len}-token context"
                )
            batch.append(ids)
        prompt_tokens = sum(len(ids) for ids in batch)
        async for out in self.client.round_robin(
            {"token_ids_batch": batch}, Context()
        ):
            return out["embeddings"], prompt_tokens
        raise RuntimeError("embed endpoint returned no response")


def build_local_pipeline(
    engine: AsyncEngine,
    tokenizer,
    model_name: str = "local",
    max_context_len: int = 8192,
) -> AsyncEngine:
    """OpenAI dict in → BackendOutput stream out, over an IN-PROCESS engine
    (the dynamo-run quickstart shape: no store, no transport — ref:
    EngineConfig::StaticFull, entrypoint.rs:44)."""
    pre = Preprocessor(
        tokenizer, model_name=model_name, max_context_len=max_context_len
    )
    back = Backend(tokenizer)
    return link(pre, back, engine)


async def make_kv_sink(
    card: ModelDeploymentCard, client: Client, **router_kwargs
):
    """Build + start a KV-aware routing sink for ``build_routed_pipeline``
    (ref: KvPushRouter kv_router.rs:423). Returns ``(sink, router)`` so the
    caller can ``router.stop()`` at teardown."""
    from ..router.kv_router import KvPushRouter, KvRouter

    router = KvRouter(
        client, client.endpoint.component,
        block_size=card.kv_block_size, **router_kwargs,
    )
    await router.start()
    return KvPushRouter(router), router
