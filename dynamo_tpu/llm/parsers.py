"""Streaming reasoning + tool-call parsers
(ref: lib/parsers/src/{reasoning,tool_calling} — hermes/llama/pythonic
formats, detect-start jailing heuristics at preprocessor.rs:27).

Parsers consume decoded text deltas and re-split them into
``content`` / ``reasoning_content`` / ``tool_calls``. Streaming rule: plain
content flows through immediately; the moment a start marker *might* be
forming, the tail is held back ("jailed") until it resolves — so clients
never see half a ``<tool_call>`` tag, and reasoning is never leaked as
content.
"""

from __future__ import annotations

import ast
import json
import re
import uuid
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class ParseDelta:
    content: str = ""
    reasoning: str = ""
    tool_calls: List[dict] = field(default_factory=list)

    @property
    def empty(self) -> bool:
        return not (self.content or self.reasoning or self.tool_calls)


def _tool_call_dict(name: str, arguments: str, index: int) -> dict:
    return {
        "index": index,
        "id": f"call_{uuid.uuid4().hex[:24]}",
        "type": "function",
        "function": {"name": name, "arguments": arguments},
    }


def _partial_suffix(buf: str, token: str) -> int:
    """Length of the longest suffix of ``buf`` that is a proper prefix of
    ``token`` (what must be held back in case the token continues)."""
    for n in range(min(len(token) - 1, len(buf)), 0, -1):
        if token.startswith(buf[-n:]):
            return n
    return 0


class ReasoningParser:
    """Splits ``<think>…</think>`` spans into ``reasoning_content``
    (ref: reasoning/base parser; deepseek-r1/gpt-oss style)."""

    def __init__(self, start: str = "<think>", end: str = "</think>"):
        self.start = start
        self.end = end
        self._buf = ""
        self._in_think = False

    def push(self, text: str) -> ParseDelta:
        self._buf += text
        out = ParseDelta()
        while True:
            if self._in_think:
                idx = self._buf.find(self.end)
                if idx >= 0:
                    out.reasoning += self._buf[:idx]
                    self._buf = self._buf[idx + len(self.end):]
                    self._in_think = False
                    continue
                hold = _partial_suffix(self._buf, self.end)
                emit = self._buf[: len(self._buf) - hold]
                out.reasoning += emit
                self._buf = self._buf[len(emit):]
                return out
            idx = self._buf.find(self.start)
            if idx >= 0:
                out.content += self._buf[:idx]
                self._buf = self._buf[idx + len(self.start):]
                self._in_think = True
                continue
            hold = _partial_suffix(self._buf, self.start)
            emit = self._buf[: len(self._buf) - hold]
            out.content += emit
            self._buf = self._buf[len(emit):]
            return out

    def flush(self) -> ParseDelta:
        out = ParseDelta()
        if self._in_think:
            out.reasoning = self._buf   # unterminated think: keep as reasoning
        else:
            out.content = self._buf
        self._buf = ""
        self._in_think = False
        return out


class HermesToolParser:
    """``<tool_call>{json}</tool_call>`` (hermes/qwen format)."""

    START, END = "<tool_call>", "</tool_call>"

    def __init__(self):
        self._buf = ""
        self._jailed = False
        self._count = 0

    def push(self, text: str) -> ParseDelta:
        self._buf += text
        out = ParseDelta()
        while True:
            if self._jailed:
                idx = self._buf.find(self.END)
                if idx < 0:
                    return out  # still jailed
                raw = self._buf[:idx].strip()
                self._buf = self._buf[idx + len(self.END):]
                self._jailed = False
                out.tool_calls.extend(self._parse(raw))
                continue
            idx = self._buf.find(self.START)
            if idx >= 0:
                out.content += self._buf[:idx]
                self._buf = self._buf[idx + len(self.START):]
                self._jailed = True
                continue
            hold = _partial_suffix(self._buf, self.START)
            emit = self._buf[: len(self._buf) - hold]
            out.content += emit
            self._buf = self._buf[len(emit):]
            return out

    def _parse(self, raw: str) -> List[dict]:
        try:
            obj = json.loads(raw)
        except json.JSONDecodeError:
            return []
        args = obj.get("arguments", obj.get("parameters", {}))
        call = _tool_call_dict(
            obj.get("name", ""), json.dumps(args), self._count
        )
        self._count += 1
        return [call]

    def flush(self) -> ParseDelta:
        out = ParseDelta()
        if not self._jailed:
            out.content = self._buf
        # jailed-but-unterminated: drop the partial call (never emit garbage)
        self._buf = ""
        self._jailed = False
        return out


class JsonToolParser:
    """Bare-JSON tool calls: output that *is* ``{"name": …, "parameters"|
    "arguments": …}`` (llama3-style). Jails from the first ``{`` that looks
    like a call start (detect-start heuristic, ref preprocessor.rs:27)."""

    _START = re.compile(r'\{\s*"(?:name|type)"\s*:')

    def __init__(self):
        self._buf = ""
        self._jailed = False
        self._count = 0

    def push(self, text: str) -> ParseDelta:
        self._buf += text
        out = ParseDelta()
        if not self._jailed:
            m = self._START.search(self._buf)
            if m is None:
                # hold back a potential forming start (anything from the
                # last unmatched '{' on)
                idx = self._buf.rfind("{")
                emit_to = idx if idx >= 0 else len(self._buf)
                out.content += self._buf[:emit_to]
                self._buf = self._buf[emit_to:]
                return out
            out.content += self._buf[: m.start()]
            self._buf = self._buf[m.start():]
            self._jailed = True
        # jailed: try to complete the JSON object
        obj, consumed = self._try_complete(self._buf)
        if obj is not None:
            self._buf = self._buf[consumed:]
            self._jailed = False
            out.tool_calls.extend(self._emit(obj))
        return out

    @staticmethod
    def _try_complete(buf: str):
        depth = 0
        in_str = False
        esc = False
        for i, ch in enumerate(buf):
            if esc:
                esc = False
                continue
            if ch == "\\":
                esc = True
            elif ch == '"':
                in_str = not in_str
            elif not in_str:
                if ch == "{":
                    depth += 1
                elif ch == "}":
                    depth -= 1
                    if depth == 0:
                        try:
                            return json.loads(buf[: i + 1]), i + 1
                        except json.JSONDecodeError:
                            return None, 0
        return None, 0

    def _emit(self, obj: dict) -> List[dict]:
        if "function" in obj:   # {"type":"function","function":{...}}
            obj = obj["function"]
        name = obj.get("name", "")
        args = obj.get("parameters", obj.get("arguments", {}))
        call = _tool_call_dict(name, json.dumps(args), self._count)
        self._count += 1
        return [call]

    def flush(self) -> ParseDelta:
        out = ParseDelta()
        out.content = "" if self._jailed else self._buf
        self._buf = ""
        self._jailed = False
        return out


class PythonicToolParser:
    """``[get_weather(city="SF"), search(q=1)]`` (llama4/pythonic format)."""

    _START = re.compile(r"\[\s*[A-Za-z_][\w.]*\s*\(")

    def __init__(self):
        self._buf = ""
        self._jailed = False
        self._count = 0

    def push(self, text: str) -> ParseDelta:
        self._buf += text
        out = ParseDelta()
        if not self._jailed:
            m = self._START.search(self._buf)
            if m is None:
                idx = self._buf.rfind("[")
                emit_to = idx if idx >= 0 else len(self._buf)
                out.content += self._buf[:emit_to]
                self._buf = self._buf[emit_to:]
                return out
            out.content += self._buf[: m.start()]
            self._buf = self._buf[m.start():]
            self._jailed = True
        end = self._find_close(self._buf)
        if end >= 0:
            raw = self._buf[: end + 1]
            self._buf = self._buf[end + 1:]
            self._jailed = False
            out.tool_calls.extend(self._parse(raw))
        return out

    @staticmethod
    def _find_close(buf: str) -> int:
        depth = 0
        in_str: Optional[str] = None
        for i, ch in enumerate(buf):
            if in_str:
                if ch == in_str:
                    in_str = None
                continue
            if ch in "\"'":
                in_str = ch
            elif ch == "[" or ch == "(":
                depth += 1
            elif ch == "]" or ch == ")":
                depth -= 1
                if depth == 0:
                    return i
        return -1

    def _parse(self, raw: str) -> List[dict]:
        try:
            tree = ast.parse(raw.strip(), mode="eval")
        except SyntaxError:
            return []
        if not isinstance(tree.body, ast.List):
            return []
        calls = []
        for node in tree.body.elts:
            if not isinstance(node, ast.Call):
                continue
            name = ast.unparse(node.func)
            args = {}
            for kw in node.keywords:
                try:
                    args[kw.arg] = ast.literal_eval(kw.value)
                except (ValueError, SyntaxError):
                    args[kw.arg] = ast.unparse(kw.value)
            calls.append(_tool_call_dict(
                name, json.dumps(args), self._count
            ))
            self._count += 1
        return calls

    def flush(self) -> ParseDelta:
        out = ParseDelta()
        out.content = "" if self._jailed else self._buf
        self._buf = ""
        self._jailed = False
        return out


TOOL_PARSERS = {
    "hermes": HermesToolParser,
    "json": JsonToolParser,
    "pythonic": PythonicToolParser,
}


class StreamParserPipeline:
    """Reasoning parser feeding a tool-call parser (either optional)."""

    def __init__(self, reasoning: Optional[str] = None,
                 tool_calls: Optional[str] = None):
        self.reasoning = ReasoningParser() if reasoning else None
        self.tools = (TOOL_PARSERS[tool_calls]()
                      if tool_calls else None)

    def push(self, text: str) -> ParseDelta:
        if self.reasoning is not None:
            d = self.reasoning.push(text)
            if self.tools is not None and d.content:
                td = self.tools.push(d.content)
                d.content = td.content
                d.tool_calls.extend(td.tool_calls)
            return d
        if self.tools is not None:
            return self.tools.push(text)
        return ParseDelta(content=text)

    def flush(self) -> ParseDelta:
        out = ParseDelta()
        if self.reasoning is not None:
            d = self.reasoning.flush()
            out.reasoning += d.reasoning
            if self.tools is not None and d.content:
                td = self.tools.push(d.content)
                out.content += td.content
                out.tool_calls.extend(td.tool_calls)
            else:
                out.content += d.content
        if self.tools is not None:
            d = self.tools.flush()
            out.content += d.content
            out.tool_calls.extend(d.tool_calls)
        return out
