"""Preprocessor operator: OpenAI-shaped request → tokenized request.

Role-equivalent to the reference's ``OpenAIPreprocessor`` forward edge
(ref: lib/llm/src/preprocessor.rs:158): apply model defaults, render the
chat template (jinja2), tokenize, and build sampling/stop configuration.
OpenAI SSE delta folding happens in the frontend (``llm/openai.py``), so the
backward edge here is identity over :class:`BackendOutput`.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jinja2

from ..runtime.context import Context
from ..runtime.engine import Operator
from ..tracing import trace_span
from .protocols import PreprocessedRequest, SamplingOptions, StopConditions
from .tokenizer import Tokenizer

# Generic fallback template (models ship their own via tokenizer_config.json)
DEFAULT_CHAT_TEMPLATE = (
    "{% for m in messages %}"
    "<|{{ m['role'] }}|>\n{{ m['content'] }}\n"
    "{% endfor %}"
    "{% if add_generation_prompt %}<|assistant|>\n{% endif %}"
)


class PromptTemplate:
    """Jinja2 chat-template renderer (ref: preprocessor/prompt/*)."""

    def __init__(self, template: Optional[str] = None):
        self._env = jinja2.Environment(
            loader=jinja2.BaseLoader(), keep_trailing_newline=True,
            trim_blocks=True, lstrip_blocks=True,
        )
        self._env.globals["raise_exception"] = self._raise
        self._template = self._env.from_string(
            template or DEFAULT_CHAT_TEMPLATE
        )

    @staticmethod
    def _raise(msg: str):
        raise ValueError(f"chat template error: {msg}")

    def render(
        self,
        messages: List[Dict[str, Any]],
        *,
        add_generation_prompt: bool = True,
        bos_token: str = "",
        eos_token: str = "",
        **extra,
    ) -> str:
        return self._template.render(
            messages=messages,
            add_generation_prompt=add_generation_prompt,
            bos_token=bos_token, eos_token=eos_token, **extra,
        )


class Preprocessor(Operator):
    """Forward edge: OpenAI request dict → :class:`PreprocessedRequest`.

    Accepts either chat requests (``messages``) or completion requests
    (``prompt`` as text, or pre-tokenized as a list of ids).
    """

    def __init__(
        self,
        tokenizer: Tokenizer,
        *,
        model_name: str = "",
        default_max_tokens: int = 512,
        max_context_len: Optional[int] = None,
    ):
        self.tokenizer = tokenizer
        self.model_name = model_name
        self.default_max_tokens = default_max_tokens
        self.max_context_len = max_context_len
        self.template = PromptTemplate(tokenizer.chat_template)

    # -- forward --

    async def forward(self, request: Any, context: Context) -> Any:
        if isinstance(request, PreprocessedRequest):
            return request
        req: dict = request
        with trace_span("frontend.tokenize", context) as span:
            token_ids, formatted = self._tokenize(req)
            span.set_attr("num_tokens", len(token_ids))
        return self.build_request(req, token_ids, formatted=formatted)

    def build_request(
        self, req: dict, token_ids: List[int],
        formatted: Optional[str] = None,
    ) -> PreprocessedRequest:
        """Assemble the PreprocessedRequest for already-produced token ids
        (shared by the text path and the multimodal preprocessor so
        sampling/stop/annotation semantics can never drift)."""
        if self.max_context_len and len(token_ids) >= self.max_context_len:
            raise ValueError(
                f"prompt length {len(token_ids)} exceeds context window "
                f"{self.max_context_len}"
            )
        max_tokens = int(
            req.get("max_completion_tokens") or req.get("max_tokens")
            or self.default_max_tokens
        )
        stop = req.get("stop") or []
        if isinstance(stop, str):
            stop = [stop]
        out = PreprocessedRequest(
            token_ids=token_ids,
            model=req.get("model", self.model_name),
            sampling=SamplingOptions(
                temperature=float(req.get("temperature") or 0.0),
                top_k=int(req.get("top_k") or 0),
                top_p=float(req.get("top_p") or 1.0),
                seed=req.get("seed"),
            ),
            stop=StopConditions(
                max_tokens=max_tokens,
                stop=list(stop),
                stop_token_ids=list(req.get("stop_token_ids", [])),
                eos_token_ids=list(self.tokenizer.eos_token_ids),
                ignore_eos=bool(req.get("ignore_eos", False)),
            ),
        )
        if req.get("_return_formatted_prompt"):
            # annotation parity: formatted_prompt/token_ids on request
            # (ref: preprocessor.rs:62-65 annotations)
            out.annotations["formatted_prompt"] = formatted
            out.annotations["token_ids"] = token_ids
        return out

    def _tokenize(self, req: dict):
        if "messages" in req:
            formatted = self.template.render(
                messages=req["messages"], add_generation_prompt=True
            )
            ids = self.tokenizer.encode(formatted)
            if (self.tokenizer.bos_token_id is not None
                    and (not ids or ids[0] != self.tokenizer.bos_token_id)):
                ids = [self.tokenizer.bos_token_id] + ids
            return ids, formatted
        prompt = req.get("prompt", "")
        if isinstance(prompt, list) and prompt and isinstance(prompt[0], int):
            return list(prompt), None
        if not isinstance(prompt, str):
            raise ValueError("prompt must be a string or list of token ids")
        return self.tokenizer.encode(prompt), prompt
