"""Common LLM wire types crossing the pipeline and the transport.

Role-equivalent to the reference's ``protocols/common`` types —
``PreprocessedRequest`` and ``LLMEngineOutput`` with sampling/stop options
(ref: lib/llm/src/protocols/common/*, preprocessor.rs:62-65). All types
round-trip through plain dicts so they msgpack cleanly over the transport.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class SamplingOptions:
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: Optional[int] = None

    def to_wire(self) -> dict:
        return {"temperature": self.temperature, "top_k": self.top_k,
                "top_p": self.top_p, "seed": self.seed}

    @staticmethod
    def from_wire(d: dict) -> "SamplingOptions":
        return SamplingOptions(
            temperature=float(d.get("temperature", 0.0)),
            top_k=int(d.get("top_k", 0)),
            top_p=float(d.get("top_p", 1.0)),
            seed=d.get("seed"),
        )


@dataclass
class StopConditions:
    max_tokens: int = 64
    stop: List[str] = field(default_factory=list)
    stop_token_ids: List[int] = field(default_factory=list)
    eos_token_ids: List[int] = field(default_factory=list)
    ignore_eos: bool = False

    def to_wire(self) -> dict:
        return {"max_tokens": self.max_tokens, "stop": self.stop,
                "stop_token_ids": self.stop_token_ids,
                "eos_token_ids": self.eos_token_ids,
                "ignore_eos": self.ignore_eos}

    @staticmethod
    def from_wire(d: dict) -> "StopConditions":
        return StopConditions(
            max_tokens=int(d.get("max_tokens", 64)),
            stop=list(d.get("stop", [])),
            stop_token_ids=list(d.get("stop_token_ids", [])),
            eos_token_ids=list(d.get("eos_token_ids", [])),
            ignore_eos=bool(d.get("ignore_eos", False)),
        )


@dataclass
class PreprocessedRequest:
    """Tokenized request flowing preprocessor → router → engine."""

    token_ids: List[int]
    model: str = ""
    sampling: SamplingOptions = field(default_factory=SamplingOptions)
    stop: StopConditions = field(default_factory=StopConditions)
    annotations: Dict[str, Any] = field(default_factory=dict)
    # router hints (ref: RouterConfigOverride kv_router.rs:87-93)
    router_hints: Dict[str, Any] = field(default_factory=dict)
    # multimodal payload: {positions, embeddings (binary wire array),
    # hash_token_ids} — see dynamo_tpu.multimodal
    mm: Optional[Dict[str, Any]] = None

    def to_wire(self) -> dict:
        out = {
            "token_ids": self.token_ids,
            "model": self.model,
            "sampling": self.sampling.to_wire(),
            "stop": self.stop.to_wire(),
            "annotations": self.annotations,
            "router_hints": self.router_hints,
        }
        if self.mm is not None:
            out["mm"] = self.mm
        return out

    @staticmethod
    def from_wire(d: dict) -> "PreprocessedRequest":
        return PreprocessedRequest(
            token_ids=list(d["token_ids"]),
            model=d.get("model", ""),
            sampling=SamplingOptions.from_wire(d.get("sampling", {})),
            stop=StopConditions.from_wire(d.get("stop", {})),
            annotations=dict(d.get("annotations", {})),
            router_hints=dict(d.get("router_hints", {})),
            mm=d.get("mm"),
        )


@dataclass
class BackendOutput:
    """One post-processed generation step flowing backward to the frontend."""

    token_ids: List[int]
    text: str = ""                       # completed UTF-8 delta
    finish_reason: Optional[str] = None  # stop | length | error | cancelled
    cum_tokens: int = 0                  # output tokens so far
    num_prompt_tokens: int = 0

    def to_wire(self) -> dict:
        return {"token_ids": self.token_ids, "text": self.text,
                "finish_reason": self.finish_reason,
                "cum_tokens": self.cum_tokens,
                "num_prompt_tokens": self.num_prompt_tokens}

    @staticmethod
    def from_wire(d: dict) -> "BackendOutput":
        return BackendOutput(
            token_ids=list(d.get("token_ids", [])),
            text=d.get("text", ""),
            finish_reason=d.get("finish_reason"),
            cum_tokens=int(d.get("cum_tokens", 0)),
            num_prompt_tokens=int(d.get("num_prompt_tokens", 0)),
        )
