"""Model discovery: deployment cards, registration, and the model watcher.

Role-equivalent to the reference's discovery stack (ref: lib/llm/src/
discovery/{model_entry.rs:14, watcher.rs:48,257}, model_card.rs:93,
local_model.rs:403): a worker publishes its ``ModelDeploymentCard`` (MDC) to
the store and a ``ModelEntry`` under its primary lease; the frontend's
``ModelWatcher`` reacts to puts/deletes by building/removing serving
pipelines dynamically.
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

import msgpack

from ..runtime.component import Endpoint, MDC_ROOT, MODEL_ROOT, DistributedRuntime
from ..utils.logging import get_logger
from .tokenizer import Tokenizer

log = get_logger("discovery")


@dataclass
class ModelDeploymentCard:
    """Everything a frontend needs to serve a model
    (ref: model_card.rs:93 — tokenizer, context length, template, limits)."""

    name: str
    tokenizer_json: Optional[str] = None   # serialized tokenizer.json
    tokenizer_path: Optional[str] = None   # or a local file path
    chat_template: Optional[str] = None
    context_length: int = 8192
    kv_block_size: int = 16
    migration_limit: int = 3
    eos_token_ids: list = field(default_factory=list)
    bos_token_id: Optional[int] = None
    model_type: list = field(default_factory=lambda: ["chat", "completions"])
    runtime_config: dict = field(default_factory=dict)  # ModelRuntimeConfig
    # streaming output parsers (ref: lib/parsers): "hermes"|"json"|"pythonic"
    tool_call_parser: Optional[str] = None
    # truthy → split <think>…</think> into reasoning_content
    reasoning_parser: Optional[str] = None

    def to_wire(self) -> dict:
        return {
            "name": self.name,
            "tokenizer_json": self.tokenizer_json,
            "tokenizer_path": self.tokenizer_path,
            "chat_template": self.chat_template,
            "context_length": self.context_length,
            "kv_block_size": self.kv_block_size,
            "migration_limit": self.migration_limit,
            "eos_token_ids": self.eos_token_ids,
            "bos_token_id": self.bos_token_id,
            "model_type": self.model_type,
            "runtime_config": self.runtime_config,
            "tool_call_parser": self.tool_call_parser,
            "reasoning_parser": self.reasoning_parser,
        }

    @staticmethod
    def from_wire(d: dict) -> "ModelDeploymentCard":
        return ModelDeploymentCard(
            name=d["name"],
            tokenizer_json=d.get("tokenizer_json"),
            tokenizer_path=d.get("tokenizer_path"),
            chat_template=d.get("chat_template"),
            context_length=int(d.get("context_length", 8192)),
            kv_block_size=int(d.get("kv_block_size", 16)),
            migration_limit=int(d.get("migration_limit", 3)),
            eos_token_ids=list(d.get("eos_token_ids", [])),
            bos_token_id=d.get("bos_token_id"),
            model_type=list(d.get("model_type", ["chat", "completions"])),
            runtime_config=dict(d.get("runtime_config", {})),
            tool_call_parser=d.get("tool_call_parser"),
            reasoning_parser=d.get("reasoning_parser"),
        )

    def load_tokenizer(self) -> Tokenizer:
        kw = dict(
            eos_token_ids=self.eos_token_ids,
            bos_token_id=self.bos_token_id,
            chat_template=self.chat_template,
        )
        if self.tokenizer_json:
            return Tokenizer.from_json_str(self.tokenizer_json, **kw)
        if self.tokenizer_path:
            return Tokenizer.from_file(self.tokenizer_path, **kw)
        raise ValueError(f"MDC {self.name!r} carries no tokenizer")

    def mdc_key(self) -> str:
        return f"{MDC_ROOT}{self.name}"


def model_key(name: str, instance_id: int) -> str:
    return f"{MODEL_ROOT}{name}/{instance_id}"


async def register_llm(
    endpoint: Endpoint,
    card: ModelDeploymentCard,
    instance_id: Optional[int] = None,
) -> None:
    """Publish the MDC + a lease-attached ModelEntry
    (ref: bindings rust/lib.rs:146 register_llm, local_model.rs:403)."""
    runtime = endpoint.runtime
    await runtime.store.put(
        card.mdc_key(), msgpack.packb(card.to_wire(), use_bin_type=True)
    )
    entry = {
        "name": card.name,
        "namespace": endpoint.component.namespace.name,
        "component": endpoint.component.name,
        "endpoint": endpoint.name,
        "model_type": card.model_type,
    }
    key = model_key(card.name, instance_id or runtime.primary_lease)
    await runtime.store.put(
        key, msgpack.packb(entry, use_bin_type=True),
        lease=runtime.primary_lease,
    )
    runtime.registered_models.append((endpoint.path, key))
    log.info("registered model %s on %s", card.name, endpoint.path)


class ModelWatcher:
    """Watches the model root; builds/removes pipelines on put/delete
    (ref: discovery/watcher.rs:48, handle_put :257)."""

    def __init__(
        self,
        runtime: DistributedRuntime,
        on_add: Callable,        # (card, entry_dict) -> awaitable
        on_remove: Callable,     # (model_name) -> awaitable
    ):
        self.runtime = runtime
        self.on_add = on_add
        self.on_remove = on_remove
        self._task: Optional[asyncio.Task] = None
        self._stream = None
        # model name → set of instance keys serving it
        self._instances: Dict[str, set] = {}

    async def start(self) -> None:
        # resilient watch: survives store restarts by catch-up or snapshot
        # reconcile; during the outage we keep serving the models we know
        # about (stale-while-revalidate) rather than tearing pipelines down
        snapshot, stream = await self.runtime.store.watch_prefix_resilient(
            MODEL_ROOT,
            grace_s=self.runtime.config.store_reconcile_grace_s,
        )
        self._stream = stream
        for key, value in snapshot:
            await self._handle_put(key, value)
        self._task = asyncio.create_task(self._loop(stream))

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
        if self._stream is not None:
            await self._stream.cancel()
            self._stream = None

    async def _loop(self, stream) -> None:
        while True:
            event = await stream.next()
            if event is None:
                return
            if event["event"] == "dropped":
                continue  # the resilient stream already resynced
            try:
                if event["event"] == "put":
                    await self._handle_put(event["key"], event["value"])
                elif event["event"] == "delete":
                    await self._handle_delete(event["key"])
            except Exception:
                log.exception("model watcher event failed")

    async def _handle_put(self, key: str, value: bytes) -> None:
        entry = msgpack.unpackb(value, raw=False)
        name = entry["name"]
        known = self._instances.setdefault(name, set())
        if key in known:
            return
        first = not known
        known.add(key)
        if not first:
            return  # additional replica of an already-served model
        raw = await self.runtime.store.get(f"{MDC_ROOT}{name}")
        if raw is None:
            log.error("model %s announced but MDC missing", name)
            return
        card = ModelDeploymentCard.from_wire(msgpack.unpackb(raw, raw=False))
        await self.on_add(card, entry)

    async def _handle_delete(self, key: str) -> None:
        name = key[len(MODEL_ROOT):].rsplit("/", 1)[0]
        known = self._instances.get(name)
        if known is None:
            return
        known.discard(key)
        if not known:
            del self._instances[name]
            await self.on_remove(name)
