"""XLA compile + involuntary-remat watchdog.

Counts backend compilations per jitted function after warmup via the
``jax.monitoring`` duration-event stream and parses ``[SPMD] Involuntary
full rematerialization`` warnings into structured counters — the gate
ROADMAP item 2 (multichip) needs before it can claim a clean steady state.

Attribution works because ``/jax/core/compile/backend_compile_duration``
fires *synchronously on the compiling thread*, exactly once per real
backend compile (cache hits fire nothing — verified on jax 0.4.37). Each
jitted function the engine builds is wrapped by :func:`label`, which sets
a thread-local tag around the call; a compile event observed inside a
labelled call is attributed to that function, anything else lands in the
``<unattributed>`` bucket (e.g. incidental ``jnp`` helper compiles).

Steady-state discipline: after :func:`mark_warmup_done` every further
compile increments the *steady* counters — the thing that must stay flat
in serving. ``engine_recompiles_total{fn}`` / ``engine_involuntary_remats_
total`` surface through the worker gauges and ``bench.py``'s
``recompiles_steady_state``.
"""

from __future__ import annotations

import logging
import os
import re
import sys
import tempfile
import threading
from contextlib import contextmanager
from typing import Dict, Optional

from ..utils.hotpath import hot_path

COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
UNATTRIBUTED = "<unattributed>"

# XLA's SPMD partitioner emits this (C++ warning text, also seen via log
# capture) when it must rematerialize a full tensor because no valid
# sharding propagation exists — the multichip perf killer ROADMAP item 2
# tracks. Matched case-insensitively and tolerant of prefix noise.
REMAT_RE = re.compile(
    r"\[SPMD\]\s+Involuntary full rematerialization", re.IGNORECASE
)


class CompileWatch:
    """Process-wide compile/remat counters (jax.monitoring has no
    unregister, so one listener lives for the process; tests drive the
    singleton through :func:`reset`)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._installed = False
        self._steady = False
        self.compiles_total: Dict[str, int] = {}
        self.compiles_steady: Dict[str, int] = {}
        self.compile_secs: Dict[str, float] = {}
        self.remats_total = 0
        self.remats_steady = 0

    # --------------------------- listener ------------------------------

    def install(self) -> None:
        """Idempotently register the jax.monitoring listener (lazy jax
        import: non-device processes pay nothing)."""
        with self._lock:
            if self._installed:
                return
            self._installed = True
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(self._on_event)

    def _on_event(self, event: str, duration: float, **kwargs) -> None:
        if event != COMPILE_EVENT:
            return
        fn = getattr(self._tls, "label", None) or UNATTRIBUTED
        with self._lock:
            self.compiles_total[fn] = self.compiles_total.get(fn, 0) + 1
            self.compile_secs[fn] = (
                self.compile_secs.get(fn, 0.0) + float(duration))
            if self._steady:
                self.compiles_steady[fn] = (
                    self.compiles_steady.get(fn, 0) + 1)

    # -------------------------- attribution ----------------------------

    def label(self, fn, name: str):
        """Wrap a jitted callable so compiles during its calls attribute
        to ``name``. Nesting-safe (inner label wins, outer restored)."""
        tls = self._tls

        @hot_path
        def labelled(*args, **kwargs):
            prev = getattr(tls, "label", None)
            tls.label = name
            try:
                return fn(*args, **kwargs)
            finally:
                tls.label = prev

        labelled.__wrapped__ = fn
        labelled.__compile_label__ = name
        return labelled

    # ------------------------- remat parsing ----------------------------

    def note_remat(self, n: int = 1) -> None:
        with self._lock:
            self.remats_total += n
            if self._steady:
                self.remats_steady += n

    def scan_log_text(self, text: str) -> int:
        """Count involuntary-remat warnings in captured log/stderr text
        and fold them into the counters. Returns the number found."""
        n = len(REMAT_RE.findall(text or ""))
        if n:
            self.note_remat(n)
        return n

    # --------------------------- lifecycle ------------------------------

    def mark_warmup_done(self) -> None:
        """Enter steady state: compiles from here on are *recompiles*."""
        with self._lock:
            self._steady = True
            self.compiles_steady = {}
            self.remats_steady = 0

    def reset(self) -> None:
        """Back to warmup with zeroed counters (test isolation)."""
        with self._lock:
            self._steady = False
            self.compiles_total = {}
            self.compiles_steady = {}
            self.compile_secs = {}
            self.remats_total = 0
            self.remats_steady = 0

    # --------------------------- snapshots ------------------------------

    def steady_by_label(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.compiles_steady)

    def steady_total(self) -> int:
        with self._lock:
            return sum(self.compiles_steady.values())

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "recompiles_steady_state": sum(self.compiles_steady.values()),
                "recompiles_by_fn": dict(self.compiles_steady),
                "compiles_total": sum(self.compiles_total.values()),
                "compiles_by_fn": dict(self.compiles_total),
                "compile_secs_by_fn": dict(self.compile_secs),
                "involuntary_remats_total": self.remats_total,
                "involuntary_remats_steady": self.remats_steady,
                "steady": self._steady,
            }


class RematLogHandler(logging.Handler):
    """Folds involuntary-remat warnings that reach Python logging (jax /
    absl bridges) into the watch's counters."""

    def __init__(self, watch: "CompileWatch"):
        super().__init__(level=logging.WARNING)
        self._watch = watch

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._watch.scan_log_text(record.getMessage())
        except Exception:  # a counter must never break logging
            pass


# ------------------------- module-level singleton --------------------------

_watch = CompileWatch()
_remat_handler: Optional[RematLogHandler] = None


def get_watch() -> CompileWatch:
    return _watch


def install() -> None:
    """Register the compile listener + the remat log handler (idempotent)."""
    global _remat_handler
    _watch.install()
    if _remat_handler is None:
        _remat_handler = RematLogHandler(_watch)
        for name in ("jax", "jax._src", "absl"):
            logging.getLogger(name).addHandler(_remat_handler)


def label(fn, name: str):
    return _watch.label(fn, name)


def mark_warmup_done() -> None:
    _watch.mark_warmup_done()


def scan_log_text(text: str) -> int:
    return _watch.scan_log_text(text)


def snapshot() -> dict:
    return _watch.snapshot()


def steady_total() -> int:
    return _watch.steady_total()


def steady_by_label() -> Dict[str, int]:
    return _watch.steady_by_label()


class _StderrCapture:
    """Handle returned by :func:`capture_stderr`: ``.text()`` is everything
    written to fd 2 inside the block (so far, or in total after exit)."""

    def __init__(self, path: str):
        self._path = path
        self._final: Optional[str] = None

    def _freeze(self) -> None:
        self._final = self.text()

    def text(self) -> str:
        if self._final is not None:
            return self._final
        sys.stderr.flush()
        try:
            with open(self._path, "r", errors="replace") as f:
                return f.read()
        except OSError:
            return ""


@contextmanager
def capture_stderr():
    """Tee-free fd-level stderr capture.

    XLA's ``[SPMD] Involuntary full rematerialization`` warnings are
    emitted by C++ absl logging straight to file descriptor 2 — they never
    pass through Python's ``sys.stderr`` or the logging bridge, so a
    ``redirect_stderr`` misses them. This swaps fd 2 for a temp file via
    ``os.dup2`` for the duration of the block and yields a handle whose
    ``.text()`` can be fed to :func:`scan_log_text`.
    """
    sys.stderr.flush()
    saved_fd = os.dup(2)
    tmp = tempfile.NamedTemporaryFile(
        mode="w+", suffix=".stderr", delete=False
    )
    cap = _StderrCapture(tmp.name)
    try:
        os.dup2(tmp.fileno(), 2)
        yield cap
    finally:
        sys.stderr.flush()
        os.dup2(saved_fd, 2)
        os.close(saved_fd)
        cap._freeze()
        tmp.close()
        # replay the captured bytes onto the real stderr so the capture
        # is observability, not a muzzle
        text = cap.text()
        if text:
            sys.stderr.write(text)
            sys.stderr.flush()
        try:
            os.unlink(tmp.name)
        except OSError:
            pass


@contextmanager
def assert_no_recompiles(allow: int = 0):
    """Test helper: fail if more than ``allow`` steady-state compiles (any
    label) happen inside the block. Enters steady state if not already."""
    if not _watch.snapshot()["steady"]:
        _watch.mark_warmup_done()
    before = _watch.steady_by_label()
    yield _watch
    after = _watch.steady_by_label()
    delta = {
        k: after.get(k, 0) - before.get(k, 0)
        for k in set(before) | set(after)
        if after.get(k, 0) != before.get(k, 0)
    }
    total = sum(delta.values())
    if total > allow:
        raise AssertionError(
            f"unexpected steady-state XLA recompiles: {delta!r} "
            f"({total} > allowed {allow})"
        )
