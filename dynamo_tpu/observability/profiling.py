"""On-demand ``jax.profiler`` capture behind ``/debug/profile?ms=N``.

One capture at a time per process (the profiler is a global); traces land
in a fresh TensorBoard-loadable directory under the configured base dir
(``DYNTPU_OBS_PROFILE_DIR``, default the system temp dir). CPU-safe: the
JAX profiler produces a (host-only) trace without an accelerator, which
is what the smoke test exercises.
"""

from __future__ import annotations

import asyncio
import os
import tempfile
import threading
import time

from ..utils.logging import get_logger

log = get_logger("observability.profile")

DEFAULT_MS = 1000
MAX_MS = 30_000

_capture_lock = threading.Lock()  # one capture per process, ever


def default_base_dir() -> str:
    return os.environ.get(
        "DYNTPU_OBS_PROFILE_DIR",
        os.path.join(tempfile.gettempdir(), "dyntpu-profiles"),
    )


class ProfileBusyError(RuntimeError):
    """A capture is already running in this process."""


async def capture(ms: int, base_dir: str = "") -> dict:
    """Capture a ``ms``-millisecond profiler trace; returns metadata
    (``trace_dir`` is TensorBoard-loadable:
    ``tensorboard --logdir <trace_dir>``). Raises :class:`ProfileBusyError`
    when a capture is already in flight."""
    ms = max(1, min(int(ms), MAX_MS))
    base = base_dir or default_base_dir()
    if not _capture_lock.acquire(blocking=False):
        raise ProfileBusyError("a profile capture is already running")
    try:
        os.makedirs(base, exist_ok=True)
        trace_dir = tempfile.mkdtemp(
            prefix=time.strftime("trace-%Y%m%d-%H%M%S-"), dir=base
        )
        import jax

        t0 = time.monotonic()
        jax.profiler.start_trace(trace_dir)
        try:
            # DT301: the wait must yield the event loop — the engine keeps
            # serving (that's the point: profile it under load)
            await asyncio.sleep(ms / 1000.0)
        finally:
            jax.profiler.stop_trace()
        wall_ms = (time.monotonic() - t0) * 1000.0
    finally:
        _capture_lock.release()
    log.info("profiler trace captured to %s (%.0f ms)", trace_dir, wall_ms)
    return {
        "trace_dir": trace_dir,
        "requested_ms": ms,
        "captured_ms": round(wall_ms, 1),
        "tensorboard": f"tensorboard --logdir {trace_dir}",
    }
