"""Worker-local Prometheus gauges for the engine flight recorder.

``EngineObsGauges`` mints the ``engine_*`` gauges on a MetricsRegistry and
refreshes them from ``engine.obs_snapshot()``; ``refresh()`` doubles as the
``WorkerMetricsPublisher.obs_fn`` so the same snapshot rides the wire to
the metrics aggregator (per-worker gauges + planner signals) at the
publish cadence — one read of the recorder per interval, zero per-token
overhead.
"""

from __future__ import annotations

from typing import Dict


class EngineObsGauges:
    def __init__(self, registry, engine):
        self._engine = engine
        self._g_mfu = registry.gauge(
            "engine_mfu",
            "live model-FLOPs utilization over the trailing window "
            "(goodput FLOPs / peak; attention term included)",
        )
        self._g_mfu_class = registry.gauge(
            "engine_mfu_by_class",
            "live MFU split by step class", ["step"]
        )
        self._g_goodput = registry.gauge(
            "engine_goodput_tok_s",
            "real tokens landed per second over the trailing window",
        )
        self._g_pad_waste = registry.gauge(
            "engine_padding_waste_ratio",
            "fraction of dispatched FLOPs burnt on bucket padding",
        )
        self._g_waste = registry.gauge(
            "engine_wasted_flops_ratio",
            "fraction of dispatched FLOPs wasted, by cause", ["cause"]
        )
        self._g_recompiles = registry.gauge(
            "engine_recompiles_total",
            "steady-state XLA backend compiles per jitted function "
            "(anything nonzero after warmup is a shape leak)", ["fn"]
        )
        self._g_remats = registry.gauge(
            "engine_involuntary_remats_total",
            "XLA [SPMD] involuntary full rematerialization warnings seen",
        )
        self._g_ladder = registry.gauge(
            "engine_ladder_rungs",
            "live bucket-ladder rung count per dispatch kind "
            "(engine/ladder.py; static buckets when the ladder is off)",
            ["kind"]
        )
        self._g_ladder_splits = registry.gauge(
            "engine_ladder_splits_total",
            "bucket-ladder rungs added (each costs one steady-state "
            "compile per consuming jit family)", ["kind"]
        )
        self._g_ladder_retires = registry.gauge(
            "engine_ladder_retires_total",
            "bucket-ladder rungs retired for cold occupancy", ["kind"]
        )
        self._g_ladder_budget = registry.gauge(
            "engine_ladder_budget_remaining",
            "bucket-ladder compile budget left (0 = grid frozen)", ["kind"]
        )

    def refresh(self) -> Dict[str, float]:
        """Pull one recorder snapshot, set every gauge, return the wire
        dict for the load-metrics publisher."""
        snap = self._engine.obs_snapshot()
        if not snap:
            return {}
        self._g_mfu.set(snap.get("mfu", 0.0))
        self._g_mfu_class.labels(step="prefill").set(
            snap.get("mfu_prefill", 0.0))
        self._g_mfu_class.labels(step="decode").set(
            snap.get("mfu_decode", 0.0))
        self._g_goodput.set(snap.get("goodput_tok_s", 0.0))
        self._g_pad_waste.set(snap.get("padding_waste_ratio", 0.0))
        self._g_waste.labels(cause="padding").set(
            snap.get("padding_waste_ratio", 0.0))
        self._g_waste.labels(cause="spec_reject").set(
            snap.get("spec_reject_waste_ratio", 0.0))
        for fn, n in (snap.get("recompiles_by_fn") or {}).items():
            self._g_recompiles.labels(fn=fn).set(n)
        self._g_remats.set(snap.get("involuntary_remats_total", 0))
        for kind in ("decode", "prefill"):
            n_rungs = snap.get(f"ladder_{kind}_rungs_n")
            if n_rungs is None:
                continue
            self._g_ladder.labels(kind=kind).set(n_rungs)
            self._g_ladder_splits.labels(kind=kind).set(
                snap.get(f"ladder_{kind}_splits_total", 0))
            self._g_ladder_retires.labels(kind=kind).set(
                snap.get(f"ladder_{kind}_retires_total", 0))
            self._g_ladder_budget.labels(kind=kind).set(
                snap.get(f"ladder_{kind}_budget_remaining", 0))
        # the wire snapshot carries scalars only (msgpack-friendly, and the
        # aggregator's zero-default reads stay flat)
        return {
            k: v for k, v in snap.items()
            if isinstance(v, (int, float))
        }
