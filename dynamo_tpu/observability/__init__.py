"""Engine flight recorder: live MFU/goodput accounting, compile-and-remat
watchdog, on-demand TPU profiles.

Layout:

* :mod:`.flops` — the ONE analytic FLOPs/parameter model (attention term
  included) shared with ``bench.py``
* :mod:`.stepstats` — per-engine-step records + windowed live gauges
* :mod:`.compilewatch` — per-jitted-function XLA recompile counters and
  ``[SPMD]`` involuntary-remat parsing
* :mod:`.profiling` — ``/debug/profile?ms=N`` jax.profiler capture
* :mod:`.gauges` — worker-local ``engine_*`` Prometheus gauges
* :mod:`.report` — ``python -m dynamo_tpu.observability`` JSONL report

Nothing here imports jax at module scope except via the engine's own lazy
paths, so control-plane processes (frontend, aggregator, planner) can use
the package without paying a backend import.
"""

from .flops import FlopsModel, active_param_count, param_count, peak_flops
from .stepstats import StepRecord, StepStats

__all__ = [
    "FlopsModel",
    "StepRecord",
    "StepStats",
    "active_param_count",
    "param_count",
    "peak_flops",
]
