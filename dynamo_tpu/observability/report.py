"""Where-did-the-time-go report over captured stepstats JSONL.

``python -m dynamo_tpu.observability <stepstats.jsonl>`` renders the
records a serving run captured (``DYNTPU_OBS_STEPSTATS_PATH``) into a
per-step-class accounting: device-window time, token goodput, padding and
spec-reject FLOPs waste — the offline view of the live gauges.
"""

from __future__ import annotations

import json
from typing import Dict, List, TextIO


def load_records(fh: TextIO) -> List[dict]:
    records = []
    for line in fh:
        line = line.strip()
        if not line:
            continue
        records.append(json.loads(line))
    return records


def _pct(part: float, whole: float) -> str:
    return f"{100.0 * part / whole:5.1f}%" if whole else "  n/a "


def render_report(records: List[dict]) -> str:
    """Plain-text report; deterministic for the golden test."""
    if not records:
        return "no step records\n"
    by_kind: Dict[str, List[dict]] = {}
    for r in records:
        by_kind.setdefault(r.get("kind", "?"), []).append(r)
    t0 = min(r["t_dispatch"] for r in records)
    t1 = max(r.get("t_land") or r["t_dispatch"] for r in records)
    wall = max(t1 - t0, 1e-9)
    tot_disp = sum(r.get("flops_dispatched", 0.0) for r in records)
    tot_good = sum(r.get("flops_goodput", 0.0) for r in records)
    tot_real = sum(r.get("flops_real", 0.0) for r in records)
    tot_tokens = sum(r.get("goodput_tokens", 0) for r in records)
    lines = [
        "engine flight recorder — where did the time go",
        "=" * 62,
        f"records: {len(records)}   wall: {wall:.3f}s   "
        f"goodput: {tot_tokens} tok ({tot_tokens / wall:.1f} tok/s)",
        "",
        f"{'class':<12} {'steps':>6} {'tok':>8} {'pad tok':>8} "
        f"{'busy s':>8} {'share':>6} {'waste':>6}",
        "-" * 62,
    ]
    for kind in sorted(by_kind):
        rs = by_kind[kind]
        busy = sum(max((r.get("t_land") or r["t_dispatch"])
                       - r["t_dispatch"], 0.0) for r in rs)
        disp = sum(r.get("flops_dispatched", 0.0) for r in rs)
        good = sum(r.get("flops_goodput", 0.0) for r in rs)
        tok = sum(r.get("goodput_tokens", 0) for r in rs)
        pad = sum(r.get("padded_tokens", 0) - r.get("real_tokens", 0)
                  for r in rs)
        lines.append(
            f"{kind:<12} {len(rs):>6} {tok:>8} {pad:>8} {busy:>8.3f} "
            f"{_pct(disp, tot_disp)} {_pct(disp - good, disp)}"
        )
    lines += [
        "-" * 62,
        f"padding waste:     {_pct(tot_disp - tot_real, tot_disp)} "
        f"of dispatched FLOPs",
        f"spec-reject waste: {_pct(tot_real - tot_good, tot_disp)} "
        f"of dispatched FLOPs",
        f"goodput FLOPs:     {_pct(tot_good, tot_disp)} of dispatched",
    ]
    spec_drafted = sum(r.get("spec_drafted", 0) for r in records)
    spec_accepted = sum(r.get("spec_accepted", 0) for r in records)
    if spec_drafted:
        lines.append(
            f"spec acceptance:   {spec_accepted}/{spec_drafted} "
            f"({100.0 * spec_accepted / spec_drafted:.1f}%)"
        )
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="python -m dynamo_tpu.observability",
        description="render captured stepstats JSONL into a "
                    "where-did-the-time-go report",
    )
    p.add_argument("jsonl", help="stepstats JSONL path "
                                 "(DYNTPU_OBS_STEPSTATS_PATH capture)")
    args = p.parse_args(argv)
    with open(args.jsonl) as fh:
        records = load_records(fh)
    sys.stdout.write(render_report(records))
    return 0
