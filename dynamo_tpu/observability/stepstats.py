"""Per-engine-step flight records + windowed live gauges.

The engine appends one :class:`StepRecord` per dispatched unit of device
work (a prefill chunk, an autopilot decode window, a spec verify window)
with ONLY host-known Python ints — batch occupancy, bucketed vs real token
counts, attended-context sums — and stamps the landing time when the
``_BatchingFetcher`` completes the window's (already planned) device_get.
No instrumentation ever touches a device array, so the recorder adds zero
host syncs to the hot path (dynalint-enforced).

:class:`StepStats` aggregates the records into windowed gauges:

* ``mfu`` — goodput model-FLOPs / (elapsed * peak * n_chips), split
  ``mfu_prefill`` / ``mfu_decode`` by step class, plus ``mfu_dispatched``
  counting everything the chip executed (padding included)
* ``goodput_tok_s`` — real tokens landed per second
* ``padding_waste_ratio`` — dispatched FLOPs burnt on bucket padding
* ``wasted_flops_ratio{cause=padding|spec_reject}`` — where the
  non-goodput FLOPs went

The FLOPs accounting uses the shared analytic model
(:mod:`.flops` — attention term included, not just ``2·N·params``).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Deque, Dict, Optional

from ..utils.hotpath import hot_path
from .flops import FlopsModel

# step classes
PREFILL = "prefill"
DECODE = "decode"
SPEC_VERIFY = "spec_verify"


@dataclass
class StepRecord:
    """One dispatched unit of device work, host-side metadata only."""

    kind: str                 # prefill | decode | spec_verify
    t_dispatch: float         # monotonic, at enqueue on the step thread
    t_land: float = 0.0       # monotonic, when the window's fetch landed
    bucket: int = 0           # compiled bucket this dispatch padded up to
    rows: int = 0             # padded batch rows the program computes
    live_rows: int = 0        # rows carrying a scheduled sequence
    padded_tokens: int = 0    # tokens the compiled program computes
    real_tokens: int = 0      # tokens backed by real sequence positions
    goodput_tokens: int = 0   # tokens that advanced a sequence (landing)
    context_sum: int = 0      # sum of attended context over real tokens
    spec_drafted: int = 0
    spec_accepted: int = 0
    # filled by StepStats.commit from the shared FLOPs model
    flops_dispatched: float = 0.0
    flops_real: float = 0.0
    flops_goodput: float = 0.0


@dataclass
class _Window:
    """Running sums over the committed records inside the live window."""

    steps: int = 0
    goodput_tokens: int = 0
    real_tokens: int = 0
    padded_tokens: int = 0
    flops_dispatched: float = 0.0
    flops_goodput: float = 0.0
    flops_padding_waste: float = 0.0
    flops_spec_waste: float = 0.0
    flops_goodput_prefill: float = 0.0
    flops_goodput_decode: float = 0.0
    spec_drafted: int = 0
    spec_accepted: int = 0

    def add(self, r: StepRecord, sign: int = 1) -> None:
        self.steps += sign
        self.goodput_tokens += sign * r.goodput_tokens
        self.real_tokens += sign * r.real_tokens
        self.padded_tokens += sign * r.padded_tokens
        self.flops_dispatched += sign * r.flops_dispatched
        self.flops_goodput += sign * r.flops_goodput
        self.flops_padding_waste += sign * (r.flops_dispatched - r.flops_real)
        self.flops_spec_waste += sign * (r.flops_real - r.flops_goodput)
        if r.kind == PREFILL:
            self.flops_goodput_prefill += sign * r.flops_goodput
        else:
            self.flops_goodput_decode += sign * r.flops_goodput
        self.spec_drafted += sign * r.spec_drafted
        self.spec_accepted += sign * r.spec_accepted


class StepStats:
    """Thread-safe windowed aggregator over :class:`StepRecord` commits.

    Commits arrive from the fetch/executor threads; ``snapshot()`` is read
    from the event loop (publisher, spans, bench). The window is a deque
    pruned by landing time, so gauges always describe the last
    ``window_s`` seconds of *landed* device work."""

    def __init__(
        self,
        flops_model: FlopsModel,
        *,
        n_chips: int = 1,
        peak_flops: float = 1e12,
        window_s: float = 10.0,
        capacity: int = 8192,
        jsonl_path: str = "",
        clock=time.monotonic,
    ):
        self.flops_model = flops_model
        self.n_chips = max(1, n_chips)
        self.peak_flops = max(peak_flops, 1.0)
        self.window_s = window_s
        self.capacity = capacity
        self.jsonl_path = jsonl_path
        self._clock = clock
        self._lock = threading.Lock()
        self._records: Deque[StepRecord] = deque()
        self._win = _Window()
        self._t_start = clock()       # window floor (reset at warmup end)
        self._warmup_done = False
        self._jsonl_fh = None
        # lifetime totals (never pruned) — survive window rollover
        self.total_steps = 0
        self.total_goodput_tokens = 0
        # per-(kind, bucket) occupancy, cumulative since warmup:
        # "kind:bucket" -> [dispatches, real_units, padded_units] in bucket
        # units (rows for decode/spec windows, tokens for prefill chunks).
        # The adaptive bucket ladder (engine/ladder.py) consumes this via
        # bucket_occupancy() and takes its own deltas.
        self._bucket_occ: Dict[str, list] = {}
        # snapshot cache: span recording reads this per request; recomputing
        # the window sums each time would scale with request rate
        self._snap_cache: Optional[Dict[str, float]] = None
        self._snap_cache_t = 0.0

    # ------------------------------ commit -----------------------------

    @hot_path
    def commit(self, rec: StepRecord) -> None:
        """Finalize one landed record (fetch/executor thread; Python ints
        only — no device access). The padded-shape FLOPs scale the real
        attention term by the padding ratio, a documented lower bound for
        gather-style attention that materialises the full bucket."""
        fm = self.flops_model
        rec.flops_real = fm.step_flops(rec.real_tokens, rec.context_sum)
        if rec.real_tokens > 0:
            padded_ctx = rec.context_sum * rec.padded_tokens / rec.real_tokens
        else:
            padded_ctx = 0.0
        rec.flops_dispatched = fm.step_flops(rec.padded_tokens, padded_ctx)
        goodput_ctx = (rec.context_sum * rec.goodput_tokens
                       / rec.real_tokens if rec.real_tokens else 0.0)
        rec.flops_goodput = fm.step_flops(rec.goodput_tokens, goodput_ctx)
        with self._lock:
            self._records.append(rec)
            self._win.add(rec)
            self.total_steps += 1
            self.total_goodput_tokens += rec.goodput_tokens
            if rec.bucket > 0:
                occ = self._bucket_occ.setdefault(
                    f"{rec.kind}:{rec.bucket}", [0, 0, 0])
                occ[0] += 1
                occ[1] += (rec.real_tokens if rec.kind == PREFILL
                           else rec.live_rows)
                occ[2] += rec.bucket
            self._snap_cache = None
            self._prune_locked(self._clock())
        if self.jsonl_path:
            self._write_jsonl(rec)

    def _prune_locked(self, now: float) -> None:
        floor = now - self.window_s
        while self._records and (
                self._records[0].t_land < floor
                or len(self._records) > self.capacity):
            self._win.add(self._records.popleft(), sign=-1)

    def _write_jsonl(self, rec: StepRecord) -> None:
        line = json.dumps(asdict(rec), separators=(",", ":"))
        with self._lock:
            if self._jsonl_fh is None:
                self._jsonl_fh = open(self.jsonl_path, "a")
            self._jsonl_fh.write(line + "\n")
            self._jsonl_fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._jsonl_fh is not None:
                self._jsonl_fh.close()
                self._jsonl_fh = None

    # ---------------------------- lifecycle ----------------------------

    def mark_warmup_done(self) -> None:
        """Drop everything recorded so far: compiles and cold caches make
        warmup windows unrepresentative, and bench/steady-state gauges
        must describe the measured loop only."""
        with self._lock:
            self._records.clear()
            self._win = _Window()
            self._t_start = self._clock()
            self._warmup_done = True
            self.total_steps = 0
            self.total_goodput_tokens = 0
            self._bucket_occ.clear()
            self._snap_cache = None

    def bucket_occupancy(self) -> Dict[str, tuple]:
        """Cumulative per-(kind, bucket) occupancy since warmup.

        ``"kind:bucket" -> (dispatches, real_units, padded_units)`` with
        units native to the bucket axis (rows for decode/spec, tokens for
        prefill).  Monotonic between warmup resets, so consumers (the
        bucket ladder) can delta it safely."""
        with self._lock:
            return {k: tuple(v) for k, v in self._bucket_occ.items()}

    # ---------------------------- snapshot -----------------------------

    def snapshot(self, max_age_s: float = 0.25) -> Dict[str, float]:
        """Live gauges over the trailing window (cached ``max_age_s``)."""
        now = self._clock()
        with self._lock:
            if (self._snap_cache is not None
                    and now - self._snap_cache_t <= max_age_s):
                return dict(self._snap_cache)
            self._prune_locked(now)
            w = self._win
            # elapsed: window span, floored at the warmup mark so a
            # freshly-reset recorder doesn't divide by ~0
            elapsed = min(self.window_s, max(now - self._t_start, 1e-9))
            denom = elapsed * self.peak_flops * self.n_chips
            dispatched = max(w.flops_dispatched, 0.0)
            snap = {
                "mfu": w.flops_goodput / denom,
                "mfu_prefill": w.flops_goodput_prefill / denom,
                "mfu_decode": w.flops_goodput_decode / denom,
                "mfu_dispatched": dispatched / denom,
                "goodput_tok_s": w.goodput_tokens / elapsed,
                "padding_waste_ratio": (
                    w.flops_padding_waste / dispatched if dispatched else 0.0),
                "spec_reject_waste_ratio": (
                    w.flops_spec_waste / dispatched if dispatched else 0.0),
                "steps_in_window": float(w.steps),
                "window_s": elapsed,
                "total_steps": float(self.total_steps),
                "total_goodput_tokens": float(self.total_goodput_tokens),
                "spec_drafted": float(w.spec_drafted),
                "spec_accepted": float(w.spec_accepted),
            }
            self._snap_cache = snap
            self._snap_cache_t = now
            return dict(snap)
