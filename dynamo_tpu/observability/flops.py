"""The ONE analytic FLOPs/parameter model shared by the flight recorder
(``observability/stepstats.py``) and ``bench.py``.

Two terms per processed token:

* **matmul**: ``2 * n_active_params`` — every weight participates in one
  multiply-accumulate per token (2 FLOPs/MAC). The embedding *lookup* is
  excluded (it is a gather, not a matmul); the lm_head projection is
  included. MoE models count only the ``num_experts_per_token`` routed
  experts as active.
* **attention**: ``4 * num_layers * num_heads * head_dim * context`` —
  the QK^T scores plus the PV mix, both ``num_heads * head_dim * context``
  MACs per query token. This is the term the old ``2·N·tokens`` formula
  dropped; at long contexts it dominates.

Peak FLOP/s per chip comes from public spec sheets (dense bf16; fp32
halves the MXU rate). The table lived in ``bench.py`` before PR 9.
"""

from __future__ import annotations

from typing import Optional

# Peak dense bf16 FLOP/s per chip by device kind (public spec sheets).
PEAK_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v5": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}
# Peak dense int8 OP/s per chip. v5e/v5p/v6e double the bf16 MXU rate on
# 8-bit inputs; v4 predates the int8 path and stays at its bf16 number.
PEAK_FLOPS_INT8 = {
    "v4": 275e12,
    "v5 lite": 394e12,
    "v5e": 394e12,
    "v5p": 918e12,
    "v5": 918e12,
    "v6 lite": 1836e12,
    "v6e": 1836e12,
}
# fp8 rides the same 8-bit MXU datapath as int8 on the generations that
# have it (MFU with quantized weights is measured against this roofline).
PEAK_FLOPS_FP8 = PEAK_FLOPS_INT8
DEFAULT_PEAK = 197e12        # v5e — the BASELINE.md target platform
DEFAULT_PEAK_INT8 = 394e12   # v5e 8-bit rate
CPU_PEAK = 1e12        # nominal, so CPU-fallback MFU fields stay defined


def peak_flops(device_kind: str, platform: str,
               dtype: str = "bfloat16") -> float:
    """Per-chip peak FLOP/s for a device kind string (e.g. ``"TPU v5e"``).

    Longest-key match over the table; unknown TPU kinds fall back to the
    v5e number, non-TPU platforms to the nominal CPU peak. fp32 halves a
    TPU's MXU rate; ``"int8"``/``"fp8"`` select the doubled 8-bit table
    (bf16 inputs are the spec-sheet number)."""
    if platform != "tpu":
        return CPU_PEAK
    kind = (device_kind or "").lower()
    if dtype in ("int8", "fp8", "float8_e4m3fn"):
        table, peak = PEAK_FLOPS_INT8, DEFAULT_PEAK_INT8
    else:
        table, peak = PEAK_FLOPS, DEFAULT_PEAK
    for key in sorted(table, key=len, reverse=True):
        if key in kind:
            peak = table[key]
            break
    if dtype in ("float32", "f32"):
        peak /= 2.0
    return peak


def param_count(cfg) -> int:
    """Exact parameter count of ``engine.model.init_params`` for a
    ModelConfig (checked against the real tree in test_observability)."""
    hd = cfg.head_dim_
    D, H, KV, F, L, V = (
        cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads,
        cfg.intermediate_size, cfg.num_layers, cfg.vocab_size,
    )
    attn = D * H * hd + 2 * D * KV * hd + H * hd * D   # wq, wk, wv, wo
    if cfg.is_moe:
        mlp = D * cfg.num_experts + 3 * cfg.num_experts * D * F
    else:
        mlp = 3 * D * F
    per_layer = attn + mlp + 2 * D                      # + the two norms
    total = V * D + L * per_layer + D                   # embed + final_norm
    if not cfg.tie_word_embeddings:
        total += D * V
    return total


def active_param_count(cfg) -> int:
    """Parameters doing matmul work per token: the full count minus the
    embedding table (gather, not matmul), with MoE expert weights scaled
    to the ``num_experts_per_token`` actually routed."""
    D, F, L, V = (cfg.hidden_size, cfg.intermediate_size,
                  cfg.num_layers, cfg.vocab_size)
    active = param_count(cfg) - V * D
    if cfg.tie_word_embeddings:
        # the tied table still runs as the lm_head matmul
        active += D * V
    if cfg.is_moe and cfg.num_experts > cfg.num_experts_per_token:
        inactive_experts = cfg.num_experts - cfg.num_experts_per_token
        active -= L * inactive_experts * 3 * D * F
    return active


class FlopsModel:
    """Per-step forward-FLOPs estimator for one ModelConfig.

    ``step_flops(tokens, context_sum)`` = matmul term + attention term,
    where ``context_sum`` is the sum over the step's tokens of the context
    length each token attends (position + 1)."""

    def __init__(self, model_cfg):
        self.model_cfg = model_cfg
        self.n_params = param_count(model_cfg)
        self.n_active_params = active_param_count(model_cfg)
        self.matmul_per_token = 2.0 * self.n_active_params
        # QK^T + PV: 2 matmuls of (num_heads*head_dim x context) per token
        self.attn_coef = (4.0 * model_cfg.num_layers * model_cfg.num_heads
                          * model_cfg.head_dim_)

    def step_flops(self, tokens: float, context_sum: float) -> float:
        return self.matmul_per_token * tokens + self.attn_coef * context_sum

    def sequence_context_sum(self, length: int, start: int = 0) -> int:
        """Sum of (position + 1) over positions [start, start+length) —
        the ``context_sum`` of prefilling those tokens causally."""
        if length <= 0:
            return 0
        return length * start + length * (length + 1) // 2

    def sequence_flops(self, isl: int, osl: int) -> float:
        """Total forward FLOPs to serve one (isl, osl) request: prefill
        the prompt plus decode osl tokens, attention term included."""
        total = isl + osl
        return self.step_flops(total, self.sequence_context_sum(total))
