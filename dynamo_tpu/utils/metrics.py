"""Hierarchical Prometheus metrics registries.

Namespace/component/endpoint-scoped metric factories with automatic labels,
equivalent to the reference's ``MetricsRegistry`` trait hierarchy
(ref: lib/runtime/src/metrics.rs:365, metrics/prometheus_names.rs). Backed by
``prometheus_client``; each scope shares one process ``CollectorRegistry`` and
prefixes metric names + injects scope labels.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

# Frequency buckets tuned for LLM serving latencies (TTFT/ITL in seconds),
# same role as the reference's http/service/metrics.rs histograms.
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)


class MetricsRegistry:
    """A scope (runtime / namespace / component / endpoint) that mints metrics.

    Child scopes share the root ``CollectorRegistry`` and accumulate constant
    labels, mirroring the reference's auto-labelled hierarchy.
    """

    def __init__(
        self,
        registry: Optional[CollectorRegistry] = None,
        prefix: str = "dynamo",
        const_labels: Optional[Dict[str, str]] = None,
    ):
        self.registry = registry or CollectorRegistry()
        self.prefix = prefix
        self.const_labels = dict(const_labels or {})
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def child(self, **labels: str) -> "MetricsRegistry":
        merged = dict(self.const_labels)
        merged.update(labels)
        sub = MetricsRegistry(self.registry, self.prefix, merged)
        sub._metrics = self._metrics  # share the mint cache across scopes
        sub._lock = self._lock
        return sub

    def _full_name(self, name: str) -> str:
        return f"{self.prefix}_{name}"

    def _get_or_create(self, cls, name: str, doc: str, labelnames, **kwargs):
        key = self._full_name(name)
        with self._lock:
            metric = self._metrics.get(key)
            if metric is None:
                metric = cls(
                    key,
                    doc,
                    labelnames=tuple(labelnames),
                    registry=self.registry,
                    **kwargs,
                )
                self._metrics[key] = metric
        return metric

    def _labelnames(self, extra: Sequence[str]) -> tuple:
        return tuple(self.const_labels.keys()) + tuple(extra)

    def _bind(self, metric, extra_labels: Sequence[str]):
        if extra_labels:
            return _Bound(metric, self.const_labels)
        # a metric with no labels at all cannot take .labels()
        return metric.labels(**self.const_labels) if self.const_labels else metric

    def counter(self, name: str, doc: str, extra_labels: Sequence[str] = ()):
        c = self._get_or_create(Counter, name, doc, self._labelnames(extra_labels))
        return self._bind(c, extra_labels)

    def gauge(self, name: str, doc: str, extra_labels: Sequence[str] = ()):
        g = self._get_or_create(Gauge, name, doc, self._labelnames(extra_labels))
        return self._bind(g, extra_labels)

    def histogram(
        self, name: str, doc: str, extra_labels: Sequence[str] = (), buckets=LATENCY_BUCKETS
    ):
        h = self._get_or_create(
            Histogram, name, doc, self._labelnames(extra_labels), buckets=buckets
        )
        return self._bind(h, extra_labels)

    def render(self) -> bytes:
        """Prometheus text exposition of every metric in this process scope."""
        return generate_latest(self.registry)


def validate_exposition(body: bytes) -> list:
    """Round-trip a text-exposition payload through the reference parser
    and return the parsed sample tuples.

    Raises ``ValueError`` on any conformance violation (unescaped label
    values or HELP text, malformed sample lines, duplicate series) — the
    scrape-and-validate test runs every registry through this, so nasty
    label values (newlines, quotes, backslashes) can't silently corrupt
    the exposition.
    """
    from prometheus_client.parser import text_string_to_metric_families

    samples = []
    seen = set()
    for fam in text_string_to_metric_families(body.decode("utf-8")):
        for s in fam.samples:
            key = (s.name, tuple(sorted(s.labels.items())))
            if key in seen:
                raise ValueError(f"duplicate series {key!r}")
            seen.add(key)
            samples.append(s)
    return samples


class _Bound:
    """Partially-bound metric: const labels applied, extra labels at call time."""

    def __init__(self, metric, const_labels: Dict[str, str]):
        self._metric = metric
        self._const = const_labels

    def labels(self, **extra: str):
        merged = dict(self._const)
        merged.update(extra)
        return self._metric.labels(**merged)

    def remove(self, **extra: str) -> None:
        """Drop one label-set's child series (e.g. a departed worker's
        gauges) so stale values stop being scraped. No-op if the label set
        was never observed."""
        merged = dict(self._const)
        merged.update(extra)
        try:
            values = [merged[n] for n in self._metric._labelnames]
            self._metric.remove(*values)
        except KeyError:
            pass
