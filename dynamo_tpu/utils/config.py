"""Layered runtime configuration.

Equivalent role to the reference's figment-based ``RuntimeConfig``
(ref: lib/runtime/src/config.rs:72,194-244): defaults < config file (TOML/JSON)
< environment variables, with the ``DYNTPU_`` prefix (the reference uses
``DYN_``). Typed accessors with bool/int/float coercion.
"""

from __future__ import annotations

import dataclasses
import json
import os
from pathlib import Path
from typing import Any, Optional

ENV_PREFIX = "DYNTPU_"

_TRUTHY = {"1", "true", "yes", "on"}
_FALSY = {"0", "false", "no", "off", ""}


def env_flag(name: str, default: bool = False) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    low = raw.strip().lower()
    if low in _TRUTHY:
        return True
    if low in _FALSY:
        return False
    raise ValueError(f"cannot parse boolean env {name}={raw!r}")


def env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    return default if raw is None or raw == "" else int(raw)


def env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    return default if raw is None or raw == "" else float(raw)


def env_str(name: str, default: str) -> str:
    return os.environ.get(name, default)


@dataclasses.dataclass
class RuntimeConfig:
    """Process-wide runtime settings, layered from file + env.

    Fields mirror the reference's runtime knobs (worker thread counts become
    asyncio/executor sizing here; etcd/NATS addresses become the store/
    transport addresses of our own control plane).
    """

    namespace: str = "dynamo"
    store_addr: str = "127.0.0.1:3280"  # lease-KV discovery store (etcd role)
    system_port: int = 0  # 0 = disabled; /health /live /metrics server
    system_enabled: bool = False
    request_timeout_s: float = 600.0
    # frontend admission control: 0 disables the limiter entirely
    max_concurrent_requests: int = 0
    max_queued_requests: int = 16
    retry_after_s: float = 1.0
    health_check_enabled: bool = False
    health_check_period_s: float = 10.0
    lease_ttl_s: float = 10.0  # ref: transports/etcd.rs:89-95 (10 s TTL)
    # graceful drain: in-flight streams get this long to finish before they
    # are stopped (clients migrate the remainder to another worker)
    drain_timeout_s: float = 30.0
    # store-outage survival: how long the client retries reconnecting before
    # declaring the lease lost, and the jittered-backoff pacing of the dials
    store_recover_timeout_s: float = 30.0
    store_reconnect_base_s: float = 0.25
    store_reconnect_cap_s: float = 5.0
    # after a snapshot reconcile, keys missing from the snapshot are only
    # evicted once they stay gone this long (their owner may be re-putting)
    store_reconcile_grace_s: float = 3.0
    jsonl_logging: bool = False
    log_level: str = "INFO"
    num_io_threads: int = 8
    # -- request tracing (dynamo_tpu.tracing) --
    # head-sampling ratio by trace id in [0, 1]; 0 disables span export
    # (stage_latency_seconds histograms are observed regardless)
    trace_sample_ratio: float = 0.0
    # root spans slower than this are exported even when unsampled
    # (slow-request auto-dump); 0 disables
    trace_slow_threshold_s: float = 0.0
    # JSONL span export path for the offline assembler; "" disables
    trace_export_path: str = ""
    # in-process span ring buffer (serves the /debug/traces endpoint)
    trace_buffer_size: int = 4096
    # -- speculative decoding defaults (worker flags override) --
    # "off" | "ngram"; see EngineConfig.spec_mode for semantics
    spec_mode: str = "off"
    spec_k: int = 4
    # acceptance rate below which drafting auto-disables (0 = never),
    # checked once spec_auto_disable_window draft tokens were verified
    spec_auto_disable_threshold: float = 0.0
    spec_auto_disable_window: int = 256
    # chunked prefill: per-chunk token cap so long prompts interleave with
    # running decodes (0 = whole-bucket prefill); see
    # EngineConfig.prefill_chunk_tokens
    prefill_chunk_tokens: int = 0
    # -- quantized serving (engine/quant.py) --
    # "bf16" | "int8" | "fp8": weight storage dtype (per-channel scales)
    # and paged-KV storage dtype (per-token scales); validated again by
    # EngineConfig at engine startup so a typo rejects before load
    weight_dtype: str = "bf16"
    kv_dtype: str = "bf16"
    # -- global prefix cache (dynamo_tpu.prefix) --
    # radix-tree prefix index over the tiered KVBM: engine-side tier
    # tracking + onboarding/demotion policies (attach_prefix_cache)
    prefix_enabled: bool = True
    # routers score workers by longest cached prefix from the radix
    # cluster replica instead of flat block-hash overlap
    prefix_routing: bool = True
    # matches shorter than this many leading blocks keep overlap scoring
    prefix_min_match_blocks: int = 1
    # G1 blocks one degradation evict_to_host application may demote
    prefix_evict_blocks: int = 64
    # routing score weight of host-pool / store-held prefix blocks
    # relative to device-resident G1 (= 1.0)
    prefix_tier_weight_g2: float = 0.75
    prefix_tier_weight_g4: float = 0.5
    # -- SLA planner (python -m dynamo_tpu.planner) --
    # latency statistic the SLAs are enforced on: "p99" | "p50" | "avg"
    planner_sla_quantile: str = "p99"
    # graceful-degradation ladder (shed -> clamp spec_k -> tighten
    # chunking) ordered before scaling; see planner/degradation.py
    planner_degradation_enabled: bool = True
    planner_engage_ratio: float = 1.5
    planner_release_ratio: float = 1.0
    planner_shed_tier: int = 1
    planner_spec_k_clamp: int = 1
    planner_chunk_clamp_tokens: int = 256
    # workers poll planner/{ns}/degradation and clamp their engine knobs
    # when enabled (frontends always apply tier shedding)
    planner_apply_degradation: bool = False
    # -- disaggregated prefill/decode handoff (dynamo_tpu.disagg) --
    # how long decode waits on a queued prefill before going local
    disagg_queue_wait_s: float = 60.0
    # total wall budget for one KV handoff (further capped by the
    # request's own deadline)
    disagg_handoff_timeout_s: float = 120.0
    # extra wait when a device transfer is mid-write at timeout
    disagg_inflight_grace_s: float = 30.0
    # per-attempt cap on one KV push (device transfer or relay inject)
    disagg_inject_timeout_s: float = 10.0
    # push retries after the first attempt (exponential backoff from
    # the base, always bounded by the remaining handoff deadline)
    disagg_transfer_max_retries: int = 2
    disagg_retry_backoff_base_s: float = 0.05
    # consecutive handoff failures before decode flips to local-prefill
    # for the cooldown window (exported as disagg_breaker_open)
    disagg_breaker_failure_threshold: int = 3
    disagg_breaker_cooldown_s: float = 10.0
    # orphan GC: sweep cadence + slack past an entry's deadline
    disagg_orphan_sweep_interval_s: float = 5.0
    disagg_orphan_grace_s: float = 5.0
    # -- preemption tolerance (dynamo_tpu.runtime.preemption) --
    # wait after a maintenance notice before evacuating, so short
    # seats finish in place instead of paying a handoff
    preempt_notice_grace_s: float = 2.0
    # total wall budget for evacuating all in-flight seats; seats that
    # miss the deadline fall back to Migration re-prefill
    preempt_evac_deadline_s: float = 30.0
    # max seat-state journal entries retained per worker (evacuated
    # seats are dropped oldest-first past the cap)
    preempt_journal_cap: int = 256
    # -- engine flight recorder (dynamo_tpu.observability) --
    # master switch for the per-step recorder + compile watchdog; the
    # recorder stamps host-known ints on already-planned syncs, so the
    # steady-state overhead is a few microseconds per window
    obs_enabled: bool = True
    # trailing window the live gauges (engine_mfu, engine_goodput_tok_s,
    # engine_padding_waste_ratio, ...) describe
    obs_window_s: float = 10.0
    # append every landed StepRecord as one JSON line here ("" disables);
    # render offline with `python -m dynamo_tpu.observability <path>`
    obs_stepstats_path: str = ""
    # base directory for /debug/profile?ms=N trace captures ("" = a
    # dyntpu-profiles dir under the system tempdir)
    obs_profile_dir: str = ""

    @staticmethod
    def from_settings(path: Optional[str] = None) -> "RuntimeConfig":
        cfg = RuntimeConfig()
        file_path = path or os.environ.get(ENV_PREFIX + "CONFIG")
        if file_path and Path(file_path).exists():
            data = json.loads(Path(file_path).read_text())
            for k, v in data.items():
                if hasattr(cfg, k):
                    setattr(cfg, k, v)
        # env layer wins
        cfg.namespace = env_str(ENV_PREFIX + "NAMESPACE", cfg.namespace)
        cfg.store_addr = env_str(ENV_PREFIX + "STORE_ADDR", cfg.store_addr)
        cfg.system_port = env_int(ENV_PREFIX + "SYSTEM_PORT", cfg.system_port)
        cfg.system_enabled = env_flag(ENV_PREFIX + "SYSTEM_ENABLED", cfg.system_enabled)
        cfg.request_timeout_s = env_float(
            ENV_PREFIX + "REQUEST_TIMEOUT_S", cfg.request_timeout_s
        )
        cfg.max_concurrent_requests = env_int(
            ENV_PREFIX + "MAX_CONCURRENT_REQUESTS", cfg.max_concurrent_requests
        )
        cfg.max_queued_requests = env_int(
            ENV_PREFIX + "MAX_QUEUED_REQUESTS", cfg.max_queued_requests
        )
        cfg.retry_after_s = env_float(
            ENV_PREFIX + "RETRY_AFTER_S", cfg.retry_after_s
        )
        cfg.health_check_enabled = env_flag(
            ENV_PREFIX + "HEALTH_CHECK_ENABLED", cfg.health_check_enabled
        )
        cfg.health_check_period_s = env_float(
            ENV_PREFIX + "HEALTH_CHECK_PERIOD_S", cfg.health_check_period_s
        )
        cfg.lease_ttl_s = env_float(ENV_PREFIX + "LEASE_TTL_S", cfg.lease_ttl_s)
        cfg.drain_timeout_s = env_float(
            ENV_PREFIX + "DRAIN_TIMEOUT_S", cfg.drain_timeout_s
        )
        cfg.store_recover_timeout_s = env_float(
            ENV_PREFIX + "STORE_RECOVER_TIMEOUT_S", cfg.store_recover_timeout_s
        )
        cfg.store_reconnect_base_s = env_float(
            ENV_PREFIX + "STORE_RECONNECT_BASE_S", cfg.store_reconnect_base_s
        )
        cfg.store_reconnect_cap_s = env_float(
            ENV_PREFIX + "STORE_RECONNECT_CAP_S", cfg.store_reconnect_cap_s
        )
        cfg.store_reconcile_grace_s = env_float(
            ENV_PREFIX + "STORE_RECONCILE_GRACE_S", cfg.store_reconcile_grace_s
        )
        cfg.jsonl_logging = env_flag(ENV_PREFIX + "JSONL_LOGGING", cfg.jsonl_logging)
        cfg.log_level = env_str(ENV_PREFIX + "LOG_LEVEL", cfg.log_level)
        cfg.num_io_threads = env_int(ENV_PREFIX + "IO_THREADS", cfg.num_io_threads)
        cfg.trace_sample_ratio = env_float(
            ENV_PREFIX + "TRACE_SAMPLE_RATIO", cfg.trace_sample_ratio
        )
        cfg.trace_slow_threshold_s = env_float(
            ENV_PREFIX + "TRACE_SLOW_THRESHOLD_S", cfg.trace_slow_threshold_s
        )
        cfg.trace_export_path = env_str(
            ENV_PREFIX + "TRACE_EXPORT_PATH", cfg.trace_export_path
        )
        cfg.trace_buffer_size = env_int(
            ENV_PREFIX + "TRACE_BUFFER_SIZE", cfg.trace_buffer_size
        )
        cfg.spec_mode = env_str(ENV_PREFIX + "SPEC_MODE", cfg.spec_mode)
        cfg.spec_k = env_int(ENV_PREFIX + "SPEC_K", cfg.spec_k)
        cfg.spec_auto_disable_threshold = env_float(
            ENV_PREFIX + "SPEC_AUTO_DISABLE_THRESHOLD",
            cfg.spec_auto_disable_threshold,
        )
        cfg.spec_auto_disable_window = env_int(
            ENV_PREFIX + "SPEC_AUTO_DISABLE_WINDOW",
            cfg.spec_auto_disable_window,
        )
        cfg.prefill_chunk_tokens = env_int(
            ENV_PREFIX + "PREFILL_CHUNK_TOKENS", cfg.prefill_chunk_tokens
        )
        cfg.weight_dtype = env_str(
            ENV_PREFIX + "WEIGHT_DTYPE", cfg.weight_dtype
        )
        cfg.kv_dtype = env_str(ENV_PREFIX + "KV_DTYPE", cfg.kv_dtype)
        cfg.prefix_enabled = env_flag(
            ENV_PREFIX + "PREFIX_ENABLED", cfg.prefix_enabled
        )
        cfg.prefix_routing = env_flag(
            ENV_PREFIX + "PREFIX_ROUTING", cfg.prefix_routing
        )
        cfg.prefix_min_match_blocks = env_int(
            ENV_PREFIX + "PREFIX_MIN_MATCH_BLOCKS",
            cfg.prefix_min_match_blocks,
        )
        cfg.prefix_evict_blocks = env_int(
            ENV_PREFIX + "PREFIX_EVICT_BLOCKS", cfg.prefix_evict_blocks
        )
        cfg.prefix_tier_weight_g2 = env_float(
            ENV_PREFIX + "PREFIX_TIER_WEIGHT_G2", cfg.prefix_tier_weight_g2
        )
        cfg.prefix_tier_weight_g4 = env_float(
            ENV_PREFIX + "PREFIX_TIER_WEIGHT_G4", cfg.prefix_tier_weight_g4
        )
        cfg.planner_sla_quantile = env_str(
            ENV_PREFIX + "PLANNER_SLA_QUANTILE", cfg.planner_sla_quantile
        )
        cfg.planner_degradation_enabled = env_flag(
            ENV_PREFIX + "PLANNER_DEGRADATION_ENABLED",
            cfg.planner_degradation_enabled,
        )
        cfg.planner_engage_ratio = env_float(
            ENV_PREFIX + "PLANNER_ENGAGE_RATIO", cfg.planner_engage_ratio
        )
        cfg.planner_release_ratio = env_float(
            ENV_PREFIX + "PLANNER_RELEASE_RATIO", cfg.planner_release_ratio
        )
        cfg.planner_shed_tier = env_int(
            ENV_PREFIX + "PLANNER_SHED_TIER", cfg.planner_shed_tier
        )
        cfg.planner_spec_k_clamp = env_int(
            ENV_PREFIX + "PLANNER_SPEC_K_CLAMP", cfg.planner_spec_k_clamp
        )
        cfg.planner_chunk_clamp_tokens = env_int(
            ENV_PREFIX + "PLANNER_CHUNK_CLAMP_TOKENS",
            cfg.planner_chunk_clamp_tokens,
        )
        cfg.planner_apply_degradation = env_flag(
            ENV_PREFIX + "PLANNER_APPLY_DEGRADATION",
            cfg.planner_apply_degradation,
        )
        cfg.disagg_queue_wait_s = env_float(
            ENV_PREFIX + "DISAGG_QUEUE_WAIT_S", cfg.disagg_queue_wait_s
        )
        cfg.disagg_handoff_timeout_s = env_float(
            ENV_PREFIX + "DISAGG_HANDOFF_TIMEOUT_S",
            cfg.disagg_handoff_timeout_s,
        )
        cfg.disagg_inflight_grace_s = env_float(
            ENV_PREFIX + "DISAGG_INFLIGHT_GRACE_S",
            cfg.disagg_inflight_grace_s,
        )
        cfg.disagg_inject_timeout_s = env_float(
            ENV_PREFIX + "DISAGG_INJECT_TIMEOUT_S",
            cfg.disagg_inject_timeout_s,
        )
        cfg.disagg_transfer_max_retries = env_int(
            ENV_PREFIX + "DISAGG_TRANSFER_MAX_RETRIES",
            cfg.disagg_transfer_max_retries,
        )
        cfg.disagg_retry_backoff_base_s = env_float(
            ENV_PREFIX + "DISAGG_RETRY_BACKOFF_BASE_S",
            cfg.disagg_retry_backoff_base_s,
        )
        cfg.disagg_breaker_failure_threshold = env_int(
            ENV_PREFIX + "DISAGG_BREAKER_FAILURE_THRESHOLD",
            cfg.disagg_breaker_failure_threshold,
        )
        cfg.disagg_breaker_cooldown_s = env_float(
            ENV_PREFIX + "DISAGG_BREAKER_COOLDOWN_S",
            cfg.disagg_breaker_cooldown_s,
        )
        cfg.disagg_orphan_sweep_interval_s = env_float(
            ENV_PREFIX + "DISAGG_ORPHAN_SWEEP_INTERVAL_S",
            cfg.disagg_orphan_sweep_interval_s,
        )
        cfg.disagg_orphan_grace_s = env_float(
            ENV_PREFIX + "DISAGG_ORPHAN_GRACE_S", cfg.disagg_orphan_grace_s
        )
        cfg.preempt_notice_grace_s = env_float(
            ENV_PREFIX + "PREEMPT_NOTICE_GRACE_S", cfg.preempt_notice_grace_s
        )
        cfg.preempt_evac_deadline_s = env_float(
            ENV_PREFIX + "PREEMPT_EVAC_DEADLINE_S",
            cfg.preempt_evac_deadline_s,
        )
        cfg.preempt_journal_cap = env_int(
            ENV_PREFIX + "PREEMPT_JOURNAL_CAP", cfg.preempt_journal_cap
        )
        cfg.obs_enabled = env_flag(
            ENV_PREFIX + "OBS_ENABLED", cfg.obs_enabled
        )
        cfg.obs_window_s = env_float(
            ENV_PREFIX + "OBS_WINDOW_S", cfg.obs_window_s
        )
        cfg.obs_stepstats_path = env_str(
            ENV_PREFIX + "OBS_STEPSTATS_PATH", cfg.obs_stepstats_path
        )
        cfg.obs_profile_dir = env_str(
            ENV_PREFIX + "OBS_PROFILE_DIR", cfg.obs_profile_dir
        )
        return cfg

    @property
    def store_host(self) -> str:
        return self.store_addr.rsplit(":", 1)[0]

    @property
    def store_port(self) -> int:
        return int(self.store_addr.rsplit(":", 1)[1])
