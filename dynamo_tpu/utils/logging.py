"""Structured logging with W3C trace-context propagation.

JSONL or human-readable logs plus ``traceparent`` create/parse for
cross-process distributed tracing, carried in transport message headers
(ref: lib/runtime/src/logging.rs:50,138,157-171 — ``DistributedTraceContext``,
traceparent in NATS headers).
"""

from __future__ import annotations

import json
import logging
import os
import re
import secrets
import sys
import time
from dataclasses import dataclass
from typing import Optional

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


@dataclass(frozen=True)
class TraceContext:
    """W3C trace-context: 16-byte trace id, 8-byte span id, flags."""

    trace_id: str
    span_id: str
    flags: str = "01"

    @staticmethod
    def new() -> "TraceContext":
        return TraceContext(
            trace_id=secrets.token_hex(16), span_id=secrets.token_hex(8)
        )

    @staticmethod
    def parse(traceparent: str) -> Optional["TraceContext"]:
        m = _TRACEPARENT_RE.match(traceparent.strip().lower())
        if not m:
            return None
        version, trace_id, span_id, flags = m.groups()
        # version ff is reserved-invalid by the W3C spec (§4.1); all-zero
        # ids are likewise invalid
        if version == "ff":
            return None
        if trace_id == "0" * 32 or span_id == "0" * 16:
            return None
        return TraceContext(trace_id=trace_id, span_id=span_id, flags=flags)

    def child(self) -> "TraceContext":
        """New span in the same trace (what we put on outgoing messages)."""
        return TraceContext(
            trace_id=self.trace_id, span_id=secrets.token_hex(8), flags=self.flags
        )

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{self.flags}"


class JsonlFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": round(time.time(), 6),
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        for key in ("trace_id", "span_id", "request_id", "component", "endpoint"):
            val = getattr(record, key, None)
            if val is not None:
                entry[key] = val
        if record.exc_info:
            entry["exception"] = self.formatException(record.exc_info)
        return json.dumps(entry, separators=(",", ":"))


_CONFIGURED = False


def init_logging(level: Optional[str] = None, jsonl: Optional[bool] = None) -> None:
    """Idempotent process-wide logging setup (DYNTPU_LOG_LEVEL / _JSONL_LOGGING)."""
    global _CONFIGURED
    if _CONFIGURED:
        return
    from .config import env_flag, env_str

    level = level or env_str("DYNTPU_LOG_LEVEL", "INFO")
    jsonl = env_flag("DYNTPU_JSONL_LOGGING", False) if jsonl is None else jsonl
    handler = logging.StreamHandler(sys.stderr)
    if jsonl:
        handler.setFormatter(JsonlFormatter())
    else:
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s %(levelname)-5s %(name)s: %(message)s",
                datefmt="%H:%M:%S",
            )
        )
    root = logging.getLogger("dynamo_tpu")
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    root.addHandler(handler)
    root.propagate = False
    _CONFIGURED = True


def get_logger(name: str) -> logging.Logger:
    init_logging()
    return logging.getLogger(f"dynamo_tpu.{name}")
