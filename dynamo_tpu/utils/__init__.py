"""Host-side utilities: layered config, JSONL logging with W3C trace context,
hierarchical Prometheus metrics (ref: lib/runtime/src/{config.rs,logging.rs,
metrics.rs})."""
