"""Hot-path marker for dynalint (``dynamo_tpu.analysis``).

``@hot_path`` is a zero-cost annotation: it returns the function
unchanged at runtime. Its only effect is static — dynalint treats the
body of a decorated function as a serving hot path and applies the
strict DT1xx host-sync rules there, even in modules outside the
analyzer's hot-module allowlist.

Use it on functions that run per-token or per-batch in the serving
loop (dispatch, fetch, unpack, schedule). Do not use it on setup,
weight-loading, or teardown code; a ``jax.device_get`` there is fine.
"""

from __future__ import annotations

from typing import Callable, TypeVar

F = TypeVar("F", bound=Callable)


def hot_path(fn: F) -> F:
    """Mark ``fn`` as a serving hot path for static analysis (no-op)."""
    fn.__dynalint_hot_path__ = True
    return fn
