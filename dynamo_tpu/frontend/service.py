"""OpenAI-compatible HTTP service (aiohttp).

Role-equivalent to the reference's axum ``HttpService``
(ref: lib/llm/src/http/service/service_v2.rs:125, openai.rs:209,439) with the
same surface: ``/v1/chat/completions``, ``/v1/completions``, ``/v1/models``,
health + Prometheus metrics, SSE streaming with aggregation for
``stream=false``, and client-disconnect → context.kill propagation
(ref: http/service/disconnect.rs).
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Dict, List, Optional

from aiohttp import web

from .. import tracing
from ..llm import openai as oai
from ..llm.protocols import BackendOutput
from ..runtime.context import Context
from ..runtime.engine import AsyncEngine
from ..runtime.transport import ERR_TIMEOUT, EngineError
from ..utils.logging import TraceContext, get_logger
from ..utils.metrics import MetricsRegistry

log = get_logger("frontend.http")

# per-request deadline override (milliseconds); clamped to the service's
# configured ceiling so a client cannot buy unbounded worker time
TIMEOUT_HEADER = "X-Request-Timeout-Ms"

# request priority tier (integer, higher = more important); under graceful
# degradation the planner orders admission to shed tiers below a cutoff
TIER_HEADER = "X-Request-Tier"
DEFAULT_TIER = 1


class AdmissionError(Exception):
    """Request shed by admission control → HTTP status + Retry-After."""

    def __init__(self, status: int, retry_after_s: float, reason: str):
        super().__init__(reason)
        self.status = status
        self.retry_after_s = retry_after_s


class AdmissionController:
    """Concurrency/queue-depth limiter: shed doomed work at the door.

    Up to ``max_concurrency`` requests run; the next ``max_queue`` wait
    their turn (bounded by the request deadline); everything beyond that is
    rejected immediately with 429 + ``Retry-After`` instead of being
    accepted into a melt-down (ref: the busy-threshold rejection of
    push_router.rs:58-63, lifted to the frontend door).

    Slot handoff: a release with waiters queued passes the slot to the
    oldest waiter without touching the active count, so the limiter is FIFO
    and never overshoots.

    Tier-aware shedding: when the planner's degradation ladder sets
    ``min_tier`` > 0, requests tagged with a lower tier are rejected at the
    door regardless of free capacity — the cheapest relief valve, released
    first as pressure falls.
    """

    def __init__(self, max_concurrency: int, max_queue: int = 0,
                 retry_after_s: float = 1.0, min_tier: int = 0):
        self.max_concurrency = max_concurrency
        self.max_queue = max_queue
        self.retry_after_s = retry_after_s
        self.min_tier = min_tier
        self._active = 0
        self._queue: List[asyncio.Future] = []
        self.num_admitted = 0
        self.num_shed = 0
        self.num_tier_shed = 0

    @property
    def active(self) -> int:
        return self._active

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    async def acquire(self, deadline: Optional[float] = None,
                      tier: int = DEFAULT_TIER) -> None:
        if tier < self.min_tier:
            self.num_shed += 1
            self.num_tier_shed += 1
            raise AdmissionError(
                429, self.retry_after_s,
                f"tier {tier} shed under degradation "
                f"(min admitted tier {self.min_tier})",
            )
        if self._active < self.max_concurrency:
            self._active += 1
            self.num_admitted += 1
            return
        if len(self._queue) >= self.max_queue:
            self.num_shed += 1
            raise AdmissionError(
                429, self.retry_after_s,
                f"admission queue full ({self._active} active, "
                f"{len(self._queue)} queued)",
            )
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._queue.append(fut)
        timeout = None
        if deadline is not None:
            timeout = max(deadline - time.monotonic(), 0.001)
        try:
            await asyncio.wait_for(fut, timeout)
            self.num_admitted += 1
        except asyncio.TimeoutError:
            self._discard(fut)
            self.num_shed += 1
            raise AdmissionError(
                503, self.retry_after_s,
                "deadline expired while queued for admission",
            ) from None
        except asyncio.CancelledError:
            self._discard(fut)
            if fut.done() and not fut.cancelled():
                self.release()  # the slot was already handed to us
            raise

    def _discard(self, fut: asyncio.Future) -> None:
        try:
            self._queue.remove(fut)
        except ValueError:
            pass

    def release(self) -> None:
        while self._queue:
            fut = self._queue.pop(0)
            if not fut.done():
                fut.set_result(None)  # slot handed over; active unchanged
                return
        self._active -= 1


@dataclass
class ModelEntry:
    """A served model: its pipeline engine + capability flags
    (ref: discovery/model_entry.rs:14, model_type.rs:33)."""

    name: str
    engine: AsyncEngine          # OpenAI dict in → BackendOutput stream out
    chat: bool = True
    completions: bool = True
    created: int = field(default_factory=lambda: int(time.time()))
    metadata: dict = field(default_factory=dict)
    tool_call_parser: Optional[str] = None
    reasoning_parser: Optional[str] = None
    # embeddings pipeline (llm.entrypoint.EmbeddingsPipeline); None when the
    # backing engine has no encode path (e.g. mocker)
    embed_engine: Optional[Any] = None

    def make_parser(self):
        """Fresh per-request stream parser pipeline (or None)."""
        if not (self.tool_call_parser or self.reasoning_parser):
            return None
        from ..llm.parsers import StreamParserPipeline

        return StreamParserPipeline(
            reasoning=self.reasoning_parser,
            tool_calls=self.tool_call_parser,
        )


class ModelManager:
    """Name → entry registry the watcher populates dynamically
    (ref: service_v2.rs:30 State/ModelManager)."""

    def __init__(self):
        self._models: Dict[str, ModelEntry] = {}

    def register(self, entry: ModelEntry) -> None:
        log.info("model registered: %s", entry.name)
        self._models[entry.name] = entry

    def remove(self, name: str) -> Optional[ModelEntry]:
        entry = self._models.pop(name, None)
        if entry:
            log.info("model removed: %s", name)
        return entry

    def get(self, name: str) -> Optional[ModelEntry]:
        return self._models.get(name)

    def list(self) -> List[ModelEntry]:
        return list(self._models.values())

    def __contains__(self, name: str) -> bool:
        return name in self._models


def percentile(samples: List[float], q: float) -> Optional[float]:
    """Linear-interpolated percentile of an unsorted sample list."""
    if not samples:
        return None
    xs = sorted(samples)
    if len(xs) == 1:
        return xs[0]
    pos = (len(xs) - 1) * q
    lo = int(pos)
    frac = pos - lo
    hi = min(lo + 1, len(xs) - 1)
    return xs[lo] + (xs[hi] - xs[lo]) * frac


class WindowStats:
    """Per-window request aggregates for the SLA planner
    (ref: the Prometheus series planner_core.py:193 observe_metrics pulls;
    here collected in-process and published on the store).

    Latency samples are kept in bounded reservoirs (uniform replacement,
    seeded RNG) so the drained window carries p50/p99 tails — the planner
    enforces SLAs on percentiles, not averages that hide a melting tail."""

    RESERVOIR = 2048

    def __init__(self, reservoir: int = RESERVOIR) -> None:
        self.reservoir = reservoir
        self._rng = random.Random(0)
        self.reset()

    def reset(self) -> None:
        self.num_requests = 0
        self.isl_sum = 0
        self.osl_sum = 0
        self.ttft_sum = 0.0
        self.ttft_count = 0
        self.itl_sum = 0.0
        self.itl_count = 0
        self.ttft_samples: List[float] = []
        self.itl_samples: List[float] = []
        self._ttft_seen = 0
        self._itl_seen = 0

    def _sample(self, samples: List[float], seen: int, value: float) -> None:
        if len(samples) < self.reservoir:
            samples.append(value)
        else:
            j = self._rng.randrange(seen)
            if j < self.reservoir:
                samples[j] = value

    def record_ttft(self, value_s: float) -> None:
        self.ttft_sum += value_s
        self.ttft_count += 1
        self._ttft_seen += 1
        self._sample(self.ttft_samples, self._ttft_seen, value_s)

    def record_itl(self, value_s: float) -> None:
        self.itl_sum += value_s
        self.itl_count += 1
        self._itl_seen += 1
        self._sample(self.itl_samples, self._itl_seen, value_s)

    def drain(self) -> dict:
        """Snapshot + reset; averages/percentiles are None when nothing was
        observed."""
        out = {
            "num_requests": self.num_requests,
            "isl_avg": (self.isl_sum / self.num_requests
                        if self.num_requests else None),
            "osl_avg": (self.osl_sum / self.num_requests
                        if self.num_requests else None),
            "ttft_avg_s": (self.ttft_sum / self.ttft_count
                           if self.ttft_count else None),
            "itl_avg_s": (self.itl_sum / self.itl_count
                          if self.itl_count else None),
            "ttft_p50_s": percentile(self.ttft_samples, 0.50),
            "ttft_p99_s": percentile(self.ttft_samples, 0.99),
            "itl_p50_s": percentile(self.itl_samples, 0.50),
            "itl_p99_s": percentile(self.itl_samples, 0.99),
        }
        self.reset()
        return out


class HttpService:
    def __init__(
        self,
        manager: Optional[ModelManager] = None,
        *,
        metrics: Optional[MetricsRegistry] = None,
        host: str = "0.0.0.0",
        port: int = 8000,
        max_concurrent_requests: Optional[int] = None,
        max_queued_requests: int = 16,
        request_timeout_s: Optional[float] = None,
        retry_after_s: float = 1.0,
    ):
        self.manager = manager or ModelManager()
        self.host = host
        self.port = port
        self.request_timeout_s = request_timeout_s
        self.admission: Optional[AdmissionController] = None
        if max_concurrent_requests is not None:
            self.admission = AdmissionController(
                max_concurrent_requests, max_queued_requests, retry_after_s
            )
        self.metrics = metrics or MetricsRegistry(prefix="dynamo_frontend")
        m = self.metrics
        self._m_requests = m.counter(
            "http_requests_total", "HTTP requests", ["model", "endpoint", "status"]
        )
        self._m_inflight = m.gauge(
            "http_inflight", "in-flight requests", ["model"]
        )
        self._m_shed = m.counter(
            "admission_shed_total", "requests shed by admission control",
            ["endpoint", "status"],
        )
        self._m_admitted = m.counter(
            "admission_admitted_total", "requests admitted", ["endpoint"]
        )
        self._m_queue_depth = m.gauge(
            "admission_queue_depth", "requests waiting for an admission slot"
        )
        self._m_min_tier = m.gauge(
            "admission_min_tier",
            "lowest admitted request tier (degradation ladder cutoff)"
        )
        self._m_tier_shed = m.counter(
            "admission_tier_shed_total",
            "requests shed for being below the degradation tier cutoff",
            ["endpoint"],
        )
        self._m_active = m.gauge(
            "admission_active", "requests holding an admission slot"
        )
        self._m_ttft = m.histogram(
            "ttft_seconds", "time to first token", ["model"]
        )
        self._m_itl = m.histogram(
            "itl_seconds", "inter-token latency", ["model"]
        )
        self._m_duration = m.histogram(
            "request_seconds", "request duration", ["model"]
        )
        self.window_stats = WindowStats()
        # stage_latency_seconds{stage=...} from trace spans, observed for
        # every span regardless of the export sampling knob
        tracing.get_tracer().attach_metrics(self.metrics)
        self._runner: Optional[web.AppRunner] = None
        self.app = self._build_app()

    def _build_app(self) -> web.Application:
        app = web.Application()
        app.add_routes([
            web.post("/v1/chat/completions", self._chat),
            web.post("/v1/completions", self._completions),
            web.post("/v1/embeddings", self._embeddings),
            web.post("/v1/responses", self._responses),
            web.get("/v1/models", self._models),
            web.get("/health", self._health),
            web.get("/live", self._live),
            web.get("/metrics", self._metrics_route),
        ])
        return app

    # ------------------------- lifecycle -------------------------------

    async def start(self) -> None:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        # resolve the ephemeral port
        for s in self._runner.sites:
            server = getattr(s, "_server", None)
            if server and server.sockets:
                self.port = server.sockets[0].getsockname()[1]
        log.info("http frontend listening on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        tracing.get_tracer().detach_metrics(self.metrics)
        if self._runner:
            await self._runner.cleanup()
            self._runner = None

    # ----------------------- admission / deadlines ----------------------

    def _request_ctx(self, request: web.Request):
        """(Context, upstream span id): the context carries the request
        deadline — the configured ceiling, tightened (never widened) by an
        ``X-Request-Timeout-Ms`` header — and continues an incoming W3C
        ``traceparent`` trace when the caller sent one, so frontend spans
        parent under the caller's span."""
        timeout_s = self.request_timeout_s
        hdr = request.headers.get(TIMEOUT_HEADER)
        if hdr is not None:
            try:
                asked = float(hdr) / 1000.0
            except ValueError:
                asked = 0.0
            if asked > 0:
                timeout_s = asked if timeout_s is None else min(asked, timeout_s)
        trace = parent = None
        tp = request.headers.get("traceparent")
        if tp:
            upstream = TraceContext.parse(tp)
            if upstream is not None:
                trace = upstream.child()
                parent = upstream.span_id
        return Context.with_timeout(timeout_s, trace=trace), parent

    @staticmethod
    def _request_tier(request: web.Request, body: Optional[dict] = None) -> int:
        """Priority tier from the ``X-Request-Tier`` header (or a ``tier``
        body field); malformed values get the default tier, not an error."""
        raw = request.headers.get(TIER_HEADER)
        if raw is None and body is not None:
            raw = body.get("tier")
        if raw is None:
            return DEFAULT_TIER
        try:
            return int(raw)
        except (TypeError, ValueError):
            return DEFAULT_TIER

    def apply_degradation(self, actions: dict) -> None:
        """Apply the planner's degradation orders to admission (the
        ``shed_low_tier`` ladder step); spec/chunk clamps are worker-side."""
        min_tier = int(actions.get("min_tier") or 0)
        if self.admission is not None:
            self.admission.min_tier = min_tier
        self._m_min_tier.set(min_tier)
        log.info("degradation orders applied: min_tier=%d level=%s",
                 min_tier, actions.get("level"))

    async def _admit(
        self, endpoint: str, model: str, ctx: Context,
        tier: int = DEFAULT_TIER,
    ) -> Optional[web.Response]:
        """Acquire an admission slot; a Response means the request was shed."""
        if self.admission is None:
            return None
        span = tracing.get_tracer().start_span("frontend.admission", ctx)
        pre_tier_shed = self.admission.num_tier_shed
        try:
            await self.admission.acquire(deadline=ctx.deadline, tier=tier)
        except AdmissionError as e:
            span.set_status("error", f"shed:{e.status}")
            span.end()
            self._m_shed.labels(endpoint=endpoint, status=str(e.status)).inc()
            if self.admission.num_tier_shed > pre_tier_shed:
                self._m_tier_shed.labels(endpoint=endpoint).inc()
            self._m_requests.labels(
                model=model, endpoint=endpoint, status=str(e.status)
            ).inc()
            self._m_queue_depth.set(self.admission.queue_depth)
            return web.json_response(
                {"error": {"message": str(e), "type": "overloaded_error"}},
                status=e.status,
                headers={"Retry-After": str(max(1, round(e.retry_after_s)))},
            )
        span.end()
        self._m_admitted.labels(endpoint=endpoint).inc()
        self._m_queue_depth.set(self.admission.queue_depth)
        self._m_active.set(self.admission.active)
        return None

    def _release(self) -> None:
        if self.admission is not None:
            self.admission.release()
            self._m_queue_depth.set(self.admission.queue_depth)
            self._m_active.set(self.admission.active)

    @staticmethod
    def _engine_status(e: EngineError) -> int:
        if e.code == ERR_TIMEOUT:
            return 504
        # draining surfaces only when migration exhausted its retries with
        # every instance draining — a transient 503, like unavailability
        return 503 if e.code in ("unavailable", "overloaded",
                                 "draining") else 500

    # --------------------------- routes --------------------------------

    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response({
            "status": "healthy" if self.manager.list() else "no_models",
            "models": [e.name for e in self.manager.list()],
        })

    async def _live(self, request: web.Request) -> web.Response:
        return web.json_response({"live": True})

    async def _metrics_route(self, request: web.Request) -> web.Response:
        return web.Response(
            body=self.metrics.render(),
            content_type="text/plain", charset="utf-8",
        )

    async def _models(self, request: web.Request) -> web.Response:
        return web.json_response(oai.models_response(
            [{"name": e.name, "created": e.created} for e in self.manager.list()]
        ))

    async def _chat(self, request: web.Request) -> web.StreamResponse:
        return await self._serve(request, kind="chat")

    async def _completions(self, request: web.Request) -> web.StreamResponse:
        return await self._serve(request, kind="completion")

    async def _embeddings(self, request: web.Request) -> web.Response:
        """/v1/embeddings — encode-only engine step
        (ref: openai.rs:714 embeddings route)."""
        endpoint = "/v1/embeddings"
        try:
            body = await request.json()
        except Exception:
            return self._err(400, "invalid JSON body", "na", endpoint)
        model = body.get("model", "")
        inputs = body.get("input")
        if inputs is None or inputs == "" or inputs == []:
            return self._err(400, "missing 'input'", model, endpoint)
        entry = self.manager.get(model)
        if entry is None:
            return self._err(404, f"model {model!r} not found", model,
                             endpoint)
        if entry.embed_engine is None:
            return self._err(
                400, f"model {model!r} does not support embeddings",
                model, endpoint,
            )
        ctx, _upstream = self._request_ctx(request)
        shed = await self._admit(endpoint, model, ctx,
                                 tier=self._request_tier(request, body))
        if shed is not None:
            return shed
        self._m_inflight.labels(model=model).inc()
        t0 = time.monotonic()
        try:
            vectors, prompt_tokens = await entry.embed_engine.embed(inputs)
            self._m_requests.labels(
                model=model, endpoint=endpoint, status="200"
            ).inc()
            return web.json_response({
                "object": "list",
                "model": model,
                "data": [
                    {"object": "embedding", "index": i, "embedding": v}
                    for i, v in enumerate(vectors)
                ],
                "usage": {"prompt_tokens": prompt_tokens,
                          "total_tokens": prompt_tokens},
            })
        except EngineError as e:
            return self._err(self._engine_status(e), str(e), model, endpoint)
        except ValueError as e:
            return self._err(400, str(e), model, endpoint)
        except Exception:
            log.exception("embeddings request failed")
            return self._err(500, "internal error", model, endpoint)
        finally:
            self._release()
            self._m_inflight.labels(model=model).dec()
            self._m_duration.labels(model=model).observe(
                time.monotonic() - t0
            )

    async def _responses(self, request: web.Request) -> web.StreamResponse:
        """/v1/responses — the OpenAI Responses surface over the chat
        pipeline (ref: openai.rs:714)."""
        endpoint = "/v1/responses"
        try:
            body = await request.json()
        except Exception:
            return self._err(400, "invalid JSON body", "na", endpoint)
        model = body.get("model", "")
        try:
            chat_body = oai.responses_to_chat(body)
        except oai.RequestError as e:
            return self._err(400, str(e), model, endpoint)
        entry = self.manager.get(model)
        if entry is None:
            return self._err(404, f"model {model!r} not found", model,
                             endpoint)
        if not entry.chat:
            return self._err(400, f"model {model!r} does not support chat",
                             model, endpoint)
        ctx, _upstream = self._request_ctx(request)
        shed = await self._admit(endpoint, model, ctx,
                                 tier=self._request_tier(request, body))
        if shed is not None:
            return shed
        rid = oai.response_id()
        stream_mode = bool(body.get("stream", False))
        self._m_inflight.labels(model=model).inc()
        t0 = time.monotonic()
        try:
            outputs = entry.engine.generate(chat_body, ctx)
            outputs = self._observe(outputs, model, t0)
            chunks = oai.chat_stream(
                outputs, rid, model, parser=entry.make_parser()
            )
            if stream_mode:
                return await self._sse_events(
                    request, oai.responses_stream(chunks, rid, model),
                    ctx, model, endpoint,
                )
            agg = await oai.aggregate_chat(chunks)
            self._m_requests.labels(
                model=model, endpoint=endpoint, status="200"
            ).inc()
            return web.json_response(oai.chat_to_response(agg, rid, model))
        except EngineError as e:
            return self._err(self._engine_status(e), str(e), model, endpoint)
        except ValueError as e:
            return self._err(400, str(e), model, endpoint)
        except asyncio.CancelledError:
            ctx.kill()
            raise
        except Exception:
            log.exception("request %s failed", rid)
            return self._err(500, "internal error", model, endpoint)
        finally:
            self._release()
            self._m_inflight.labels(model=model).dec()
            self._m_duration.labels(model=model).observe(
                time.monotonic() - t0
            )

    async def _sse_events(
        self, request: web.Request, events, ctx: Context, model: str,
        endpoint: str,
    ) -> web.StreamResponse:
        """SSE writer for typed (event, payload) streams (Responses API)."""
        resp = web.StreamResponse(
            status=200,
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache",
                     "Connection": "keep-alive"},
        )
        await resp.prepare(request)
        try:
            async for event, payload in events:
                await resp.write(oai.sse_event(event, payload).encode())
            # no chat-style [DONE] frame: the Responses protocol ends at
            # the typed response.completed event
            self._m_requests.labels(
                model=model, endpoint=endpoint, status="200"
            ).inc()
        except asyncio.CancelledError:
            # client disconnect surfaces as handler cancellation: kill the
            # request so the worker frees the seat, then re-raise — eating
            # the CancelledError would also absorb drain/shutdown (DT303)
            log.info("client disconnected — killing request")
            ctx.kill()
            self._m_requests.labels(
                model=model, endpoint=endpoint, status="499"
            ).inc()
            raise
        except ConnectionResetError:
            log.info("client disconnected — killing request")
            ctx.kill()
            self._m_requests.labels(
                model=model, endpoint=endpoint, status="499"
            ).inc()
        except EngineError as e:
            await resp.write(oai.sse_event(
                "error", {"error": {"message": str(e), "code": e.code}}
            ).encode())
            self._m_requests.labels(
                model=model, endpoint=endpoint,
                status=str(self._engine_status(e)),
            ).inc()
        with _suppress():
            await resp.write_eof()
        return resp

    # ------------------------ request flow ------------------------------

    async def _serve(self, request: web.Request, kind: str) -> web.StreamResponse:
        endpoint = f"/v1/{'chat/completions' if kind == 'chat' else 'completions'}"
        try:
            body = await request.json()
        except Exception:
            return self._err(400, "invalid JSON body", "na", endpoint)
        model = body.get("model", "")
        try:
            if kind == "chat":
                oai.validate_chat_request(body)
            else:
                oai.validate_completion_request(body)
        except oai.RequestError as e:
            return self._err(400, str(e), model, endpoint)
        entry = self.manager.get(model)
        if entry is None:
            return self._err(404, f"model {model!r} not found", model, endpoint)
        if kind == "chat" and not entry.chat:
            return self._err(400, f"model {model!r} does not support chat", model, endpoint)
        if kind == "completion" and not entry.completions:
            return self._err(400, f"{model!r} does not support completions", model, endpoint)

        ctx, upstream = self._request_ctx(request)
        # the root span ADOPTS the context's span id: every child minted via
        # ctx.trace parents under it, across process boundaries
        root = tracing.get_tracer().start_span(
            "frontend.request", trace=ctx.trace, parent_span_id=upstream,
            attrs={"model": model, "endpoint": endpoint}, root=True,
        )
        shed = await self._admit(endpoint, model, ctx,
                                 tier=self._request_tier(request, body))
        if shed is not None:
            root.set_status("error", f"shed:{shed.status}")
            root.end()
            return shed
        rid = oai.chat_id() if kind == "chat" else oai.completion_id()
        stream_mode = bool(body.get("stream", False))
        self._m_inflight.labels(model=model).inc()
        t0 = time.monotonic()
        try:
            outputs = entry.engine.generate(body, ctx)
            outputs = self._observe(outputs, model, t0)
            if kind == "chat":
                chunks = oai.chat_stream(
                    outputs, rid, model, parser=entry.make_parser()
                )
            else:
                chunks = oai.completion_stream(outputs, rid, model)
            if stream_mode:
                return await self._sse(request, chunks, ctx, model, endpoint)
            agg = (oai.aggregate_chat(chunks) if kind == "chat"
                   else oai.aggregate_completion(chunks))
            result = await agg
            self._m_requests.labels(model=model, endpoint=endpoint, status="200").inc()
            return web.json_response(result)
        except EngineError as e:
            root.set_status("error", e.code)
            return self._err(self._engine_status(e), str(e), model, endpoint)
        except ValueError as e:
            root.set_status("error", "bad_request")
            return self._err(400, str(e), model, endpoint)
        except asyncio.CancelledError:
            ctx.kill()
            root.set_status("error", "cancelled")
            raise
        except Exception:
            log.exception("request %s failed", rid)
            root.set_status("error", "internal")
            return self._err(500, "internal error", model, endpoint)
        finally:
            self._release()
            root.end()
            self._m_inflight.labels(model=model).dec()
            self._m_duration.labels(model=model).observe(time.monotonic() - t0)

    async def _sse(
        self, request: web.Request, chunks: AsyncIterator[dict],
        ctx: Context, model: str, endpoint: str,
    ) -> web.StreamResponse:
        resp = web.StreamResponse(
            status=200,
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache",
                     "Connection": "keep-alive"},
        )
        await resp.prepare(request)
        try:
            async for chunk in chunks:
                await resp.write(oai.sse_frame(chunk).encode())
            await resp.write(oai.SSE_DONE.encode())
            self._m_requests.labels(model=model, endpoint=endpoint, status="200").inc()
        except asyncio.CancelledError:
            # client went away: kill the request so the worker frees the slot
            # (ref: http/service/disconnect.rs), then re-raise — swallowing
            # the CancelledError would also absorb drain/shutdown (DT303)
            log.info("client disconnected — killing request")
            ctx.kill()
            self._m_requests.labels(model=model, endpoint=endpoint, status="499").inc()
            raise
        except ConnectionResetError:
            log.info("client disconnected — killing request")
            ctx.kill()
            self._m_requests.labels(model=model, endpoint=endpoint, status="499").inc()
        except EngineError as e:
            # stream already started; emit an error frame then close
            await resp.write(oai.sse_frame(
                {"error": {"message": str(e), "code": e.code}}
            ).encode())
            self._m_requests.labels(
                model=model, endpoint=endpoint,
                status=str(self._engine_status(e)),
            ).inc()
        with _suppress():
            await resp.write_eof()
        return resp

    async def _observe(
        self, outputs: AsyncIterator[BackendOutput], model: str, t0: float
    ) -> AsyncIterator[BackendOutput]:
        first = True
        prev = None
        ws = self.window_stats
        n_tokens = 0
        # the finally accounts on EVERY termination path: downstream
        # consumers (chat_stream) break at finish_reason and client
        # disconnects close the generator chain — post-loop code after the
        # async-for would never run (the planner saw num_requests=0
        # forever), and counting only finished streams would skew isl_avg
        # whenever requests abort mid-stream
        try:
            async for out in outputs:
                now = time.monotonic()
                if first:
                    self._m_ttft.labels(model=model).observe(now - t0)
                    ws.record_ttft(now - t0)
                    ws.isl_sum += out.num_prompt_tokens
                    first = False
                elif prev is not None:
                    self._m_itl.labels(model=model).observe(now - prev)
                    ws.record_itl(now - prev)
                prev = now
                # token count, not chunk count (a chunk can carry several
                # token ids, or none during stop-string holdback)
                n_tokens = (out.cum_tokens if out.cum_tokens
                            else n_tokens + len(out.token_ids))
                yield out
        finally:
            if not first:
                ws.num_requests += 1
                ws.osl_sum += n_tokens

    def _err(self, status: int, msg: str, model: str, endpoint: str) -> web.Response:
        self._m_requests.labels(
            model=model, endpoint=endpoint, status=str(status)
        ).inc()
        return web.json_response(
            {"error": {"message": msg, "type": "invalid_request_error"
                       if status == 400 else "server_error"}},
            status=status,
        )


class _suppress:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return True
