"""API frontends: OpenAI-compatible HTTP service (ref: lib/llm/src/http)."""
