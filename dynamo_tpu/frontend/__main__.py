"""Frontend process: HTTP service + model discovery in one process.

Role-equivalent to the reference's ``python -m dynamo.frontend``
(ref: components/frontend/src/dynamo/frontend/main.py): starts the OpenAI
HTTP server, watches the store for registered models, and builds a routed
pipeline per model as workers come and go.

    python -m dynamo_tpu.frontend --port 8000 --router-mode round_robin
"""

from __future__ import annotations

import argparse
import asyncio
from ..llm.discovery import ModelDeploymentCard, ModelWatcher
from ..llm.entrypoint import (
    EmbeddingsPipeline, build_routed_pipeline, make_kv_sink,
)
from ..runtime.component import DistributedRuntime
from ..runtime.signals import install_shutdown_signals
from ..runtime.tasks import spawn_logged
from ..utils.config import RuntimeConfig
from ..utils.logging import get_logger
from .service import HttpService, ModelEntry, ModelManager

log = get_logger("frontend")


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description="dynamo-tpu OpenAI frontend")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--store-addr", default=None)
    p.add_argument("--namespace", default=None)
    p.add_argument(
        "--router-mode", default="round_robin",
        choices=["round_robin", "random", "kv"],
    )
    p.add_argument(
        "--grpc-port", type=int, default=0,
        help="also serve the KServe v2 gRPC protocol on this port "
             "(0 disables; ref: grpc/service/kserve.rs)",
    )
    p.add_argument(
        "--busy-threshold", type=float, default=0.0,
        help="reject with 503 when every worker's KV usage is above this "
             "fraction (0 disables; ref: push_router.rs busy rejection)",
    )
    p.add_argument(
        "--stats-publish-interval", type=float, default=10.0,
        help="seconds between frontend_stats publishes for the planner "
             "(0 disables)",
    )
    p.add_argument(
        "--max-concurrent-requests", type=int, default=None,
        help="admission control: concurrent requests before queueing "
             "(default from DYNTPU_MAX_CONCURRENT_REQUESTS; 0/unset "
             "disables)",
    )
    p.add_argument(
        "--max-queued-requests", type=int, default=None,
        help="admission queue depth beyond which requests are shed with "
             "429 + Retry-After",
    )
    p.add_argument(
        "--request-timeout", type=float, default=None,
        help="per-request deadline in seconds, propagated end-to-end to "
             "workers (default from DYNTPU_REQUEST_TIMEOUT_S)",
    )
    return p.parse_args(argv)


async def run_frontend(args: argparse.Namespace) -> None:
    config = RuntimeConfig.from_settings()
    if args.store_addr:
        config.store_addr = args.store_addr
    if args.namespace:
        config.namespace = args.namespace
    runtime = await DistributedRuntime.from_settings(config)

    manager = ModelManager()
    max_concurrent = (args.max_concurrent_requests
                      if args.max_concurrent_requests is not None
                      else config.max_concurrent_requests)
    max_queued = (args.max_queued_requests
                  if args.max_queued_requests is not None
                  else config.max_queued_requests)
    timeout_s = (args.request_timeout if args.request_timeout is not None
                 else config.request_timeout_s)
    service = HttpService(
        manager, host=args.host, port=args.port, metrics=runtime.metrics,
        max_concurrent_requests=max_concurrent if max_concurrent else None,
        max_queued_requests=max_queued,
        request_timeout_s=timeout_s if timeout_s and timeout_s > 0 else None,
        retry_after_s=config.retry_after_s,
    )
    clients = {}
    kv_routers = {}
    monitors = {}

    async def on_add(card: ModelDeploymentCard, entry: dict) -> None:
        endpoint = (
            runtime.namespace(entry["namespace"])
            .component(entry["component"]).endpoint(entry["endpoint"])
        )
        client = await endpoint.client()
        clients[card.name] = client
        if args.busy_threshold > 0:
            from ..router.monitor import WorkerMonitor

            monitor = WorkerMonitor(
                client, busy_threshold=args.busy_threshold
            )
            await monitor.start()
            monitor.attach()
            monitors[card.name] = monitor
        sink = None
        if args.router_mode == "kv":
            sink, kv_routers[card.name] = await make_kv_sink(card, client)
        tokenizer = card.load_tokenizer()  # shared by every pipeline piece
        mm_processor = None
        mm_cfg = (card.runtime_config or {}).get("multimodal")
        if mm_cfg:
            from ..multimodal.processor import MultimodalProcessor

            encode_client = await (
                runtime.namespace(entry["namespace"])
                .component(mm_cfg["component"])
                .endpoint(mm_cfg.get("endpoint", "encode")).client()
            )
            clients[card.name + "/encode"] = encode_client
            mm_processor = MultimodalProcessor(
                tokenizer,
                tokens_per_image=int(mm_cfg["tokens_per_image"]),
                encode_client=encode_client,
            )
        engine = build_routed_pipeline(
            card, client, router_mode=args.router_mode, sink=sink,
            mm_processor=mm_processor, tokenizer=tokenizer,
        )
        # embeddings ride the worker's encode-only "embed" endpoint; the
        # card advertises the capability (mocker-backed models don't have
        # it and their requests 400 immediately)
        embed_engine = None
        if "embeddings" in card.model_type:
            embed_client = await (
                runtime.namespace(entry["namespace"])
                .component(entry["component"]).endpoint("embed").client()
            )
            clients[card.name + "/embed"] = embed_client
            embed_engine = EmbeddingsPipeline(card, embed_client,
                                              tokenizer=tokenizer)
        manager.register(ModelEntry(
            name=card.name, engine=engine,
            chat="chat" in card.model_type,
            completions="completions" in card.model_type,
            tool_call_parser=card.tool_call_parser,
            reasoning_parser=card.reasoning_parser,
            embed_engine=embed_engine,
        ))

    async def on_remove(name: str) -> None:
        manager.remove(name)
        monitor = monitors.pop(name, None)
        if monitor:
            await monitor.stop()
        router = kv_routers.pop(name, None)
        if router:
            await router.stop()
        client = clients.pop(name, None)
        if client:
            await client.stop()
        embed_client = clients.pop(name + "/embed", None)
        if embed_client:
            await embed_client.stop()
        encode_client = clients.pop(name + "/encode", None)
        if encode_client:
            await encode_client.stop()

    watcher = ModelWatcher(runtime, on_add, on_remove)
    await watcher.start()
    await service.start()

    grpc_service = None
    if args.grpc_port:
        from ..kserve import KserveGrpcService

        grpc_service = KserveGrpcService(
            manager, host=args.host, port=args.grpc_port
        )
        await grpc_service.start()

    stats_task = None
    if args.stats_publish_interval > 0:
        import msgpack

        subject = f"{runtime.namespace().name}/frontend_stats"

        async def _publish_stats():
            while True:
                await asyncio.sleep(args.stats_publish_interval)
                win = service.window_stats.drain()
                win["interval_s"] = args.stats_publish_interval
                # live pressure signals riding the same payload: admission
                # backlog and router breaker states (planner feeds)
                if service.admission is not None:
                    win["queue_depth"] = service.admission.queue_depth
                win["breaker_open"] = sum(
                    1
                    for router in kv_routers.values()
                    for state in router.breakers.states().values()
                    if state != "closed"
                )
                try:
                    await runtime.store.publish(
                        subject, msgpack.packb(win)
                    )
                except Exception as exc:
                    log.warning("frontend stats publish failed: %s", exc)

        stats_task = asyncio.create_task(_publish_stats())

    # the planner's degradation ladder orders tier shedding through the
    # store; apply it to admission as the orders move
    from ..planner.degradation import DegradationWatcher

    degradation_watcher = DegradationWatcher(
        runtime.store, runtime.namespace().name,
        service.apply_degradation,
    )
    degradation_watcher.start()

    install_shutdown_signals(
        lambda: spawn_logged(_shutdown(), name="frontend-shutdown"),
        loop=asyncio.get_running_loop(), name="frontend",
    )

    async def _shutdown():
        if stats_task is not None:
            stats_task.cancel()
        await degradation_watcher.stop()
        await watcher.stop()
        if grpc_service is not None:
            await grpc_service.stop()
        await service.stop()
        await runtime.shutdown()

    log.info("frontend ready on %s:%d", args.host, service.port)
    await runtime.shutdown_event.wait()


def main(argv=None) -> None:
    asyncio.run(run_frontend(parse_args(argv)))


if __name__ == "__main__":
    main()
