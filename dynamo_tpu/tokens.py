"""Block-aligned token sequences with rolling content hashes.

The canonical prefix-cache key math shared by the KV router's radix indexer,
the engine's prefix cache, and the KV block manager. Capability-equivalent to
the reference's standalone tokens crate (ref: lib/tokens/src/lib.rs:14-27 and
lib/llm/src/tokens.rs:44,388,479).

**Hash scheme (internally defined, framework-canonical).** The reference uses
*two* schemes: router-side unchained per-block hashes
(lib/llm/src/kv_router/indexer.rs:117-135) and KVBM-side chained sequence
hashes over packed ``[parent_hash, block_hash]`` u64 pairs
(lib/llm/src/tokens.rs:413-416). This build deliberately standardises on ONE
scheme everywhere — router index, engine KV events, and KVBM block reuse all
key on the same *chained sequence hash* so prefix matching and block reuse can
never disagree across components. The chain is
``xxh3_64(parent_seq_hash_le_u64 || token_bytes_u32_le, seed=1337)`` (root
blocks hash their token bytes alone). Hash *values* therefore differ from the
reference's; the seed (1337) and token byte encoding (u32 LE) match its
conventions.

Two hash kinds per block:
- ``block_hash``: xxh3_64 over the block's own token bytes (u32 LE).
- ``sequence_hash``: chains the parent block's sequence hash with this block's
  token bytes, so equal sequence hashes imply equal full prefixes. This is the
  key used for KV block reuse and radix-tree matching.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Iterable, Optional, Sequence

import xxhash

HASH_SEED = 1337

Token = int
BlockHash = int
SequenceHash = int


def _tokens_to_bytes(tokens: Sequence[int]) -> bytes:
    return struct.pack(f"<{len(tokens)}I", *tokens)


def compute_block_hash(tokens: Sequence[int]) -> BlockHash:
    """Content hash of one block's tokens (u32 little-endian), xxh3-64/1337."""
    return xxhash.xxh3_64_intdigest(_tokens_to_bytes(tokens), seed=HASH_SEED)


def compute_sequence_hash(
    parent: Optional[SequenceHash], tokens: Sequence[int]
) -> SequenceHash:
    """Rolling prefix hash: chain parent sequence hash with this block's bytes."""
    if parent is None:
        return compute_block_hash(tokens)
    payload = struct.pack("<Q", parent) + _tokens_to_bytes(tokens)
    return xxhash.xxh3_64_intdigest(payload, seed=HASH_SEED)


def compute_block_hashes_for_seq(
    tokens: Sequence[int], block_size: int
) -> list[SequenceHash]:
    """Sequence hashes for every *complete* block of ``tokens``.

    The router-side hot path (same role as the reference's
    ``compute_block_hash_for_seq``, indexer.rs:125, but chained — see module
    docstring): only full blocks participate in prefix matching; the ragged
    tail is ignored. Uses the native C++ path when built (native/src),
    byte-exact with the Python fallback (tests/test_native.py).
    """
    if len(tokens) >= block_size:
        native = _native_mod()
        if native is not None:
            res = native.block_hashes(tokens, block_size, HASH_SEED)
            if res is not None:
                return [int(h) for h in res[1]]
    out: list[SequenceHash] = []
    parent: Optional[SequenceHash] = None
    for start in range(0, len(tokens) - block_size + 1, block_size):
        parent = compute_sequence_hash(parent, tokens[start : start + block_size])
        out.append(parent)
    return out


_NATIVE = None
_NATIVE_TRIED = False


def _native_mod():
    global _NATIVE, _NATIVE_TRIED
    if not _NATIVE_TRIED:
        _NATIVE_TRIED = True
        try:
            from . import native as _native

            if _native.available():
                _NATIVE = _native
        except Exception:
            _NATIVE = None
    return _NATIVE


@dataclass(frozen=True)
class TokenBlock:
    """One complete, immutable block of tokens with its chained hashes."""

    tokens: tuple[int, ...]
    block_hash: BlockHash
    sequence_hash: SequenceHash
    parent_sequence_hash: Optional[SequenceHash]

    @staticmethod
    def build(
        tokens: Sequence[int], parent: Optional[SequenceHash]
    ) -> "TokenBlock":
        return TokenBlock(
            tokens=tuple(tokens),
            block_hash=compute_block_hash(tokens),
            sequence_hash=compute_sequence_hash(parent, tokens),
            parent_sequence_hash=parent,
        )


@dataclass
class TokenBlockSequence:
    """A growing token sequence chunked into fixed-size hashed blocks.

    Mirrors the reference's ``TokenBlockSequence`` (lib/llm/src/tokens.rs:479):
    append tokens one at a time or in bulk; every time a block fills, it is
    sealed into a ``TokenBlock`` with a rolling sequence hash. The ragged tail
    (``partial_tokens``) stays mutable until sealed.
    """

    block_size: int
    blocks: list[TokenBlock] = field(default_factory=list)
    partial_tokens: list[int] = field(default_factory=list)

    def __post_init__(self):
        if self.block_size <= 0:
            raise ValueError("block_size must be positive")

    @staticmethod
    def from_tokens(tokens: Sequence[int], block_size: int) -> "TokenBlockSequence":
        seq = TokenBlockSequence(block_size=block_size)
        seq.extend(tokens)
        return seq

    def __len__(self) -> int:
        return len(self.blocks) * self.block_size + len(self.partial_tokens)

    @property
    def total_tokens(self) -> int:
        return len(self)

    def tokens(self) -> list[int]:
        out: list[int] = []
        for b in self.blocks:
            out.extend(b.tokens)
        out.extend(self.partial_tokens)
        return out

    def last_sequence_hash(self) -> Optional[SequenceHash]:
        return self.blocks[-1].sequence_hash if self.blocks else None

    def sequence_hashes(self) -> list[SequenceHash]:
        return [b.sequence_hash for b in self.blocks]

    def append(self, token: int) -> Optional[TokenBlock]:
        """Append one token; returns the sealed block if this filled one."""
        self.partial_tokens.append(token)
        if len(self.partial_tokens) == self.block_size:
            block = TokenBlock.build(self.partial_tokens, self.last_sequence_hash())
            self.blocks.append(block)
            self.partial_tokens = []
            return block
        return None

    def extend(self, tokens: Iterable[int]) -> list[TokenBlock]:
        """Append many tokens; returns all blocks sealed along the way."""
        sealed: list[TokenBlock] = []
        for t in tokens:
            b = self.append(t)
            if b is not None:
                sealed.append(b)
        return sealed

    def truncate(self, num_tokens: int) -> None:
        """Drop tokens beyond ``num_tokens`` (used by migration/backtrack)."""
        if num_tokens >= len(self):
            return
        all_tokens = self.tokens()[:num_tokens]
        self.blocks = []
        self.partial_tokens = []
        self.extend(all_tokens)
